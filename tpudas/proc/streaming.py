"""Real-time ("edge") streaming drivers.

Library form of the two *_edge notebooks' polling loops (SURVEY.md
§3.2): poll the source directory, process what's new, sleep, repeat;
terminate when the spool stops growing. State is only the output
directory (crash-only): kill the process anywhere and the next run
resumes from ``get_last_processed_time`` with the edge-buffer rewind
``t1 = t_last - (ceil(edge/dt) - 1) * dt``
(low_pass_dascore_edge.ipynb:228-231) — which lands exactly one output
sample past the last emitted one, so resumed output is seam-free.

``poll_interval`` defaults to the reference's cadence clamp
``max(125 s, file duration, 3 * edge_buffer)``
(low_pass_dascore_edge.ipynb:165-173); tests inject ``sleep_fn`` and
``max_rounds``.

Stateful streaming (default): instead of the rewind, the low-pass
driver carries each filter stage's O(1) state across rounds
(tpudas.proc.stream) — no re-read, no re-filter; per-round work drops
from O(window + 2*edge) to O(window) full-rate samples and the carry
serializes beside the outputs so a crash resumes from O(1) state.
``TPUDAS_STREAM_STATEFUL=0`` (or ``stateful=False``) restores the
reference's rewind behavior; joint/mesh/window-DP runs and legacy
output folders (outputs but no carry) use the rewind path
automatically.

Fault tolerance (tpudas.resilience): each polling round runs inside a
per-round fault boundary.  Transient IO failures (an NFS hiccup, a
file the interrogator is still flushing) are retried with capped
exponential backoff + deterministic jitter; a file whose read/decode
keeps failing is quarantined in a ``.quarantine.json`` ledger beside
the carry and excluded from the spool index (slow-schedule re-probe);
only genuinely fatal errors — config/programming mistakes, the
reference's ``on_gap="raise"`` — propagate.  A retried round resumes
exactly like a crash does: the in-memory carry is dropped and
re-resolved from disk (reconcile included), so the crash-only
invariant is untouched.  See RESILIENCE.md.
"""

from __future__ import annotations

import math
import os
import time as _time

import numpy as np

from tpudas.core.timeutils import to_datetime64, to_timedelta64
from tpudas.io.spool import spool as make_spool
from tpudas.obs.health import write_health, write_prom
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.proc.lfproc import LFProc, resolve_gap_tolerance
from tpudas.proc.naming import get_filename
from tpudas.resilience.faults import (
    FaultBoundary,
    RetryPolicy,
    fault_point,
)
from tpudas.resilience.quarantine import QuarantineLedger
from tpudas.utils.logging import log_event
from tpudas.utils.profiling import Counters

__all__ = ["clamp_poll_interval", "run_lowpass_realtime", "run_rolling_realtime"]


class _EdgeHealth:
    """Per-run health bookkeeping for the realtime driver: assembles
    the ``health.json`` payload (schema: tpudas.obs.health) and drops
    it — plus the Prometheus exposition — beside the stream carry
    every round.  Enabled by ``TPUDAS_HEALTH=1`` (or the driver's
    ``health=True``); write failures are counted and swallowed.

    Integrity fields (schema v3): ``integrity_fallbacks`` is the
    per-run count of verified reads that rejected a primary artifact
    and took a degradation-ladder step; ``resource_degraded`` mirrors
    the disk-full shedding flag.  Either condition marks the snapshot
    ``degraded`` — recovery happened (or writers are shed), the
    operator should know.  Under resource pressure ``metrics.prom`` is
    shed (counted) while ``health.json`` itself keeps being written:
    it is the operator's only window into the degradation."""

    def __init__(self, folder, enabled, boundary=None):
        from tpudas.integrity.checksum import fallback_count

        self.folder = folder
        self.enabled = enabled
        self.boundary = boundary  # FaultBoundary (degradation fields)
        self.carry_resumes = 0
        self.last_error = None
        # optional detect summary (tpudas.detect) — surfaced in the
        # snapshot (and through /healthz) as a "detect" sub-object;
        # not part of the required schema, absent when detect is off
        self.detect = None
        self._fb0 = fallback_count()  # run baseline for the delta

    def integrity_fallbacks(self) -> int:
        from tpudas.integrity.checksum import fallback_count

        return fallback_count() - self._fb0

    def write(self, counters, rounds, polls, mode, round_rt, head_lag):
        if not self.enabled:
            return
        from tpudas.integrity import resource as _resource

        b = self.boundary
        fallbacks = self.integrity_fallbacks()
        res_degraded = _resource.is_degraded()
        degraded = (
            (False if b is None else b.degraded)
            or res_degraded
            or fallbacks > 0
        )
        payload_extra = (
            {} if self.detect is None else {"detect": self.detect}
        )
        write_health(
            self.folder,
            {
                **payload_extra,
                "rounds": rounds,
                "polls": polls,
                "mode": mode,
                "realtime_factor": round(counters.realtime_factor, 3),
                "round_realtime_factor": round(round_rt, 3),
                "head_lag_seconds": (
                    None if head_lag is None else round(head_lag, 3)
                ),
                "redundant_ratio": round(counters.redundant_ratio, 4),
                "carry_resume_count": self.carry_resumes,
                "last_round_wall_seconds": round(counters.last_wall, 4),
                "consecutive_failures": 0 if b is None else b.consecutive,
                "quarantined_files": (
                    0 if b is None else b.quarantined_count
                ),
                "degraded": degraded,
                "integrity_fallbacks": fallbacks,
                "resource_degraded": res_degraded,
                "last_error": self.last_error
                or (None if b is None else b.last_error),
            },
        )
        if not _resource.should_shed("prom"):
            write_prom(self.folder)


def _startup_audit(output_folder) -> None:
    """The drivers' pre-first-round fsck (tpudas.integrity.audit):
    sweep stale tmp files, verify every durable artifact, repair via
    the .prev/rebuild ladder.  Disable with
    ``TPUDAS_INTEGRITY_AUDIT=0``.  Never raises — an audit failure
    must not take down the stream it protects (counted + logged)."""
    if os.environ.get("TPUDAS_INTEGRITY_AUDIT", "1") == "0":
        return
    try:
        from tpudas.integrity.audit import audit

        report = audit(output_folder, repair=True)
        if report["issues"]:
            print(
                f"Integrity audit repaired {report['repaired']} "
                f"artifact(s) in {output_folder} "
                f"(clean={report['clean']})"
            )
    except Exception as exc:
        get_registry().counter(
            "tpudas_integrity_audit_errors_total",
            "startup integrity audits that raised (swallowed)",
        ).inc()
        log_event(
            "integrity_audit_failed",
            folder=str(output_folder),
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )


def _append_pyramid(output_folder, rnd, emitted, state) -> None:
    """Per-round serve-side hook: cascade this round's new output rows
    into the :mod:`tpudas.serve.tiles` pyramid beside the carry.

    ``emitted`` holds the round's output patches captured in memory at
    their write site (an ``LFProc.add_emit_listener`` subscription),
    so the steady-state append costs tile IO only — no index rescan,
    no re-reading files this process just wrote.  ``state["store"]`` carries the open store
    across rounds (a stat-gated refresh per round, not a re-parse);
    it is dropped to None on any failure — exactly the carry's
    crash-equivalent discipline — and any discontinuity (fresh
    folder, crashed append) falls back to the file-backed sync, so a
    retried or crash-resumed round needs no pyramid bookkeeping: disk
    is the only durable state.  A pyramid failure is counted and
    swallowed: the read side degrades (the query engine falls back to
    full-resolution files), the write side must not."""
    from tpudas.serve.tiles import CorruptStoreError, append_patches

    reg = get_registry()
    t0 = _time.perf_counter()
    try:
        with span("serve.pyramid_append", round=rnd):
            appended, state["store"] = append_patches(
                output_folder, emitted, store=state.get("store")
            )
    except Exception as exc:
        state["store"] = None  # crash-equivalent: re-resolve from disk
        reg.counter(
            "tpudas_serve_pyramid_errors_total",
            "per-round pyramid appends that failed (swallowed; the "
            "query engine falls back to full-resolution files)",
        ).inc()
        log_event(
            "pyramid_append_failed",
            round=rnd,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        from tpudas.integrity import resource as _resource

        if _resource.is_resource_error(exc):
            # disk full: flip the shedding flag so the NEXT rounds
            # skip the append instead of re-failing it
            _resource.note_pressure("pyramid", exc)
        elif isinstance(exc, CorruptStoreError):
            # the store itself is bad (torn tails, checksum-failed
            # tile): the ladder's last rung — delete + rebuild from
            # the output files, byte-identical, mid-run
            from tpudas.serve.tiles import rebuild_pyramid

            try:
                rebuild_pyramid(output_folder)
            except Exception as exc2:
                log_event(
                    "pyramid_rebuild_failed",
                    round=rnd,
                    error=f"{type(exc2).__name__}: {str(exc2)[:200]}",
                )
        return
    reg.histogram(
        "tpudas_serve_pyramid_append_seconds",
        "per-round tile-pyramid append wall time",
    ).observe(_time.perf_counter() - t0)
    if appended:
        log_event("pyramid_append", round=rnd, rows=int(appended))


def _head_lag_seconds(t2, lfp, carry) -> float | None:
    """Stream-seconds between the fiber head (newest indexed input,
    ``t2``) and the newest emitted output — the operator's "how far
    behind live am I" number.  None before the first output."""
    t_out_ns = None
    if carry is not None and carry.last_emit_ns is not None:
        t_out_ns = int(carry.last_emit_ns)
    else:
        try:
            t_out_ns = int(
                to_datetime64(lfp.get_last_processed_time())
                .astype("datetime64[ns]")
                .astype(np.int64)
            )
        except Exception:
            return None
    t2_ns = int(
        np.datetime64(t2, "ns").astype(np.int64)
    )
    return (t2_ns - t_out_ns) / 1e9


def _finite(value) -> float:
    """Coerce an index cell to a finite float (0.0 for None/NaN/junk) —
    a heterogeneous or legacy index row must degrade the metric, never
    crash the processing loop."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        return 0.0
    return v if math.isfinite(v) else 0.0


def _covered_workload(contents, t1, t2):
    """(data_seconds, channel_samples) actually present in the index
    within [t1, t2) — gaps and heterogeneous files are accounted per
    file, so round metrics stay honest across outages and rewinds."""
    lo = to_datetime64(t1).astype("datetime64[ns]")
    hi = to_datetime64(t2).astype("datetime64[ns]")
    data_ns = 0.0
    samples = 0.0
    for _, row in contents.iterrows():
        f_lo = np.datetime64(row["time_min"], "ns")
        f_hi = np.datetime64(row["time_max"], "ns")
        span_ns = (f_hi - f_lo) / np.timedelta64(1, "ns")
        ov = min(hi, f_hi) - max(lo, f_lo)
        ov_ns = ov / np.timedelta64(1, "ns")
        if ov_ns <= 0:
            continue
        data_ns += ov_ns
        n_time = _finite(row.get("ntime"))
        if span_ns > 0 and n_time > 1:
            fs = (n_time - 1) / (span_ns / 1e9)
            samples += ov_ns / 1e9 * fs * _finite(row.get("ndistance"))
    return data_ns / 1e9, samples


POLL_FLOOR_SEC = 125.0


def clamp_poll_interval(requested, file_duration, edge_buffer):
    """The reference's cadence guard
    (low_pass_dascore_edge.ipynb:165-173): the poll interval is
    ``max(125 s, file duration, 3 * edge buffer)`` — and never faster
    than requested. The absolute 125 s floor is unconditional; it
    bounds the chance of reading a file the interrogator is still
    mid-writing (the only race surface in the crash-only design).
    Tests inject ``sleep_fn`` rather than lowering the clamp."""
    return max(
        float(requested),
        POLL_FLOOR_SEC,
        float(file_duration),
        3.0 * float(edge_buffer),
    )


def run_lowpass_realtime(
    source,
    output_folder,
    start_time,
    output_sample_interval,
    edge_buffer,
    process_patch_size,
    distance=None,
    poll_interval=125.0,
    file_duration=0.0,
    max_rounds=None,
    sleep_fn=_time.sleep,
    on_round=None,
    engine=None,
    on_gap=None,
    filter_order=None,
    data_gap_tolorance=None,
    data_gap_tolerance=None,
    window_dp=None,
    counters=None,
    mesh=None,
    rolling_output_folder=None,
    rolling_window=None,
    rolling_step=None,
    stateful=None,
    carry_save_every=None,
    health=None,
    fault_policy=None,
    quarantine=True,
    pyramid=None,
    detect=None,
    detect_operators=None,
):
    """Poll ``source`` and keep the low-pass output current.

    ``engine`` / ``on_gap`` / ``filter_order`` / ``data_gap_tolorance``
    / ``window_dp`` are forwarded to :class:`LFProc` (None keeps its
    defaults), so the
    streaming path can run the cascade engine and gap policies the batch
    path has. ``mesh`` runs the round's device compute mesh-sharded: a
    :class:`jax.sharding.Mesh`, an int ``N`` (channel sharding over the
    first N devices), or — when None — ``TPUDAS_MESH=N`` from the
    environment (see :func:`tpudas.parallel.mesh.resolve_mesh`).  A
    channel-only mesh (no ``time`` axis > 1) keeps the STATEFUL path:
    the stream carry lives as a sharded, donated, device-resident
    pytree between rounds and outputs are byte-identical to the
    single-device run (PERF.md "Sharded streaming"); a time-sharded
    mesh falls back to the window/rewind path, which owns the halo
    exchange — see :attr:`LFProc.mesh`.  Pass a
    :class:`tpudas.utils.profiling.Counters` to
    accumulate throughput; each processing round also emits a
    ``realtime_round`` event with its own real-time factor.

    ``rolling_output_folder`` (with ``rolling_window`` /
    ``rolling_step``, seconds) switches the round processor to
    :class:`tpudas.proc.joint.JointProc`: every round emits BOTH the
    low-pass product and the seam-free trailing rolling mean from one
    ingest pass (BASELINE config 5, streaming form). For cross-round
    rolling-grid alignment use a ``rolling_step`` that divides
    ``output_sample_interval`` (each round's grid is anchored at its
    own resume point, which sits on the output grid).

    ``stateful`` selects the carried-filter-state execution mode
    (default: on, via ``TPUDAS_STREAM_STATEFUL`` — "0" restores the
    rewind): each round processes ONLY new full-rate samples through
    :meth:`LFProc.process_stream_increment` and persists the O(1)
    carry beside the outputs for crash-only resume.  Joint products,
    time-sharded meshes, and window-DP stay on the rewind path, as
    does a legacy output folder that has files but no carry.

    ``carry_save_every`` (default 1, or ``TPUDAS_CARRY_SAVE_EVERY``)
    persists the carry every Nth processing round instead of every
    round, so steady-state rounds skip the device→host gather + crc
    write entirely (the carry pytree stays on-device; at 10k channels
    this is the dominant per-round host traffic).  Crash-resume is
    unaffected in kind: a crash loses at most N-1 rounds of carry
    progress, and :func:`tpudas.proc.stream.reconcile_outputs` deletes
    the outputs past the saved carry on resume — they are regenerated
    byte-identically.  A clean shutdown always flushes a final save.

    ``health`` (default: ``TPUDAS_HEALTH=1``) drops an atomic
    ``health.json`` + ``metrics.prom`` in ``output_folder`` after every
    processing round (and on a crash), so a cron/node-exporter on the
    interrogator box can scrape stream liveness without touching the
    process — see tpudas.obs.health and OBSERVABILITY.md.

    ``data_gap_tolerance`` is the correctly spelled form of the
    reference's ``data_gap_tolorance``; the legacy spelling remains a
    deprecated alias (warns once) and passing both with different
    values is an error.

    ``pyramid`` (default: ``TPUDAS_PYRAMID=1``) keeps the
    :mod:`tpudas.serve.tiles` multi-resolution tile pyramid in
    ``output_folder`` current: after every processing round the rows
    newer than the pyramid head are appended and the coarser
    mean/min/max levels cascaded, so the serve stack
    (:mod:`tpudas.serve`) answers window queries at any zoom without
    re-reading output files.  The append is crash-only like the carry
    (manifest written after its tiles) and failures are counted and
    swallowed — the pyramid must never take down the stream that
    feeds it.

    ``detect`` (default: ``TPUDAS_DETECT=1``) runs the registered
    streaming detection operators (:mod:`tpudas.detect`) over each
    round's decimated output — STA/LTA triggers and rolling-RMS
    anomaly scores by default, or the ``detect_operators`` spec list
    (names / ``(name, params)`` / instances).  Results land in the
    crc-stamped events ledger and score tiles under
    ``<output_folder>/.detect/`` (queryable via ``GET /events``); the
    hook is crash-only like the pyramid (carry-committed, replayed via
    file-backed catch-up after any failure) and an operator failure is
    counted and skipped — it never takes down the stream.  See
    DETECTION.md.

    ``fault_policy`` (a :class:`tpudas.resilience.RetryPolicy`; None =
    defaults) governs the per-round fault boundary: transient/corrupt
    round failures are retried with capped exponential backoff instead
    of killing the driver, repeat-offender files are quarantined (the
    ``.quarantine.json`` ledger beside the carry; ``quarantine=False``
    disables the ledger), and only fatal errors propagate.  A retried
    round resumes exactly like a crash: the in-memory carry is dropped
    and re-resolved from disk.  See RESILIENCE.md for the taxonomy and
    the operator runbook.

    Returns the number of rounds that processed data. Terminates when a
    poll sees no new files (reference semantics) or after
    ``max_rounds`` polls (retries consume polls, so a bounded test can
    never spin forever).
    """
    if rolling_output_folder is None and (
        rolling_window is not None or rolling_step is not None
    ):
        raise ValueError(
            "rolling_window/rolling_step require rolling_output_folder "
            "(the joint-pipeline switch) — without it no rolling "
            "product would be written"
        )
    d_t = float(output_sample_interval)
    buff_out = int(np.ceil(edge_buffer / d_t))
    interval = clamp_poll_interval(poll_interval, file_duration, edge_buffer)
    start_time = to_datetime64(start_time)
    gap_tol = resolve_gap_tolerance(data_gap_tolerance, data_gap_tolorance)
    extra = {
        k: v
        for k, v in (
            ("engine", engine),
            ("on_gap", on_gap),
            ("filter_order", filter_order),
            ("data_gap_tolerance", gap_tol),
            ("window_dp", window_dp),
        )
        if v is not None
    }
    from tpudas.parallel.mesh import resolve_mesh

    mesh = resolve_mesh(mesh)
    counters = counters if counters is not None else Counters()
    if health is None:
        health = os.environ.get("TPUDAS_HEALTH", "0") == "1"
    policy = fault_policy if fault_policy is not None else RetryPolicy()
    # carry/ledger/health/pyramid all live in the output folder; it
    # must exist before the first processing round creates it
    os.makedirs(output_folder, exist_ok=True)
    # startup fsck BEFORE any persisted state (ledger, carry, pyramid)
    # is loaded: stale tmp sweep, checksum verification, .prev
    # promotion, pyramid rebuild — see tpudas.integrity.audit
    _startup_audit(output_folder)
    from tpudas.integrity import resource as _resource

    if _resource.is_degraded():
        # stale in-process pressure from a previous run: re-probe now
        _resource.probe_recovery(output_folder)
    if quarantine:
        ledger = QuarantineLedger(output_folder)
    else:
        ledger = None
    boundary = FaultBoundary(policy, ledger)
    edge_health = _EdgeHealth(output_folder, bool(health), boundary)
    reg = get_registry()
    if pyramid is None:
        pyramid = os.environ.get("TPUDAS_PYRAMID", "0") == "1"
    pyramid = bool(pyramid)
    if detect is None:
        detect = os.environ.get("TPUDAS_DETECT", "0") == "1"
    detect = bool(detect)

    if stateful is None:
        stateful = os.environ.get("TPUDAS_STREAM_STATEFUL", "1") != "0"
    # a channel-only mesh keeps the stateful path (the carry shards
    # over it, device-resident); a time-sharded mesh falls back to the
    # window/rewind path, which owns the halo exchange
    stateful = bool(stateful) and (
        rolling_output_folder is None
        and not window_dp
        and (mesh is None or int(mesh.shape.get("time", 1)) <= 1)
    )
    if carry_save_every is None:
        carry_save_every = int(
            os.environ.get("TPUDAS_CARRY_SAVE_EVERY", "") or 1
        )
    carry_save_every = max(1, int(carry_save_every))
    carry = None  # the cross-round filter state (stateful mode)
    carry_unsaved = 0  # completed rounds since the last carry save
    carry_checked = False  # disk/legacy resolution happens once
    rewind_wrote = False  # first rewind write invalidates any carry
    pyr_state = {"store": None}  # cross-round open tile store (pyramid)
    det_state = {"pipe": None}  # cross-round detect pipeline (detect)

    processed_once = False  # first PROCESSING round always starts at
    # start_time, however many empty polls precede it (a pre-existing
    # output folder must not hijack the user's start point)
    rounds = 0
    polls = 0
    prev_t2 = None  # previous round's processing head (redundancy metric)
    len_last = None  # spool size at the previous poll (None = no poll yet)
    round_rt = 0.0  # last round's realtime factor (final health snapshot)
    head_lag = None
    try:
        while True:
            polls += 1
            reg.counter(
                "tpudas_stream_polls_total", "source spool polls"
            ).inc()
            try:
                fault_point("round.body", poll=polls)
                # quarantine exclusion + index update + scan-failure
                # strikes + slow-schedule probe bookkeeping
                sp = boundary.begin_round(make_spool(source), source)
                sub = (
                    sp.select(distance=distance)
                    if distance is not None
                    else sp
                )
                n_now = len(sub)
                if (
                    len_last is not None
                    and n_now == len_last
                    and boundary.consecutive == 0
                ):
                    print("No new data was detected. Real-time processing ended successfully.")
                    break
                if n_now > 0:
                    t_body = _time.perf_counter()
                    joint_extra = {}
                    if rolling_output_folder is not None:
                        from tpudas.proc.joint import JointProc

                        lfp = JointProc(sub, mesh=mesh)
                        joint_extra = {
                            k: v
                            for k, v in (("rolling_window", rolling_window),
                                         ("rolling_step", rolling_step))
                            if v is not None
                        }
                    else:
                        lfp = LFProc(sub, mesh=mesh)
                    lfp.update_processing_parameter(
                        output_sample_interval=d_t,
                        process_patch_size=int(process_patch_size),
                        edge_buff_size=buff_out,
                        **extra,
                        **joint_extra,
                    )
                    lfp.set_output_folder(
                        output_folder, delete_existing=False
                    )
                    emitted_patches = []
                    if pyramid or detect:
                        # capture the round's output blocks at their
                        # write site for the in-memory pyramid append
                        # and the detect operators (multi-subscriber
                        # emit hook — one shared capture serves both)
                        lfp.add_emit_listener(emitted_patches.append)
                    if rolling_output_folder is not None:
                        lfp.set_rolling_output_folder(
                            rolling_output_folder, delete_existing=False
                        )
                    # committed to `rounds` only when the attempt
                    # completes — a failed attempt is a retry, not a
                    # processed round
                    rnd = rounds + 1
                    print("run number: ", rnd)
                    if stateful and not carry_checked:
                        # one-time disk resolution: resume a persisted
                        # carry, or fall back to rewind mode for a legacy
                        # folder that has outputs but no carry (its resume
                        # point is only expressible as a rewind)
                        carry_checked = True
                        from tpudas.proc.stream import (
                            carry_matches,
                            load_carry,
                            reconcile_outputs,
                        )

                        carry = load_carry(output_folder)
                        if carry is not None and not carry_matches(
                            carry, lfp, start_time
                        ):
                            raise ValueError(
                                "persisted stream carry in "
                                f"{output_folder} was produced under a "
                                "different start_time or processing "
                                "parameters; delete it (or the folder) to "
                                "change configuration"
                            )
                        if carry is not None:
                            # patch_size only shapes chunking — honor the
                            # live setting rather than the persisted one
                            carry.patch_out = int(process_patch_size)
                            reconcile_outputs(output_folder, carry)
                            log_event("stream_resume", emitted=carry.emitted)
                            edge_health.carry_resumes += 1
                            reg.counter(
                                "tpudas_stream_carry_resumes_total",
                                "rounds resumed from a persisted stream "
                                "carry",
                            ).inc()
                        else:
                            try:
                                lfp.get_last_processed_time()
                                has_outputs = True
                            except (FileNotFoundError, IndexError) as exc:
                                # the two EXPECTED "no outputs yet"
                                # signals (virgin/empty folder); a real
                                # IO error must not be misread as "no
                                # outputs" — it propagates to the fault
                                # boundary instead
                                has_outputs = False
                                log_event(
                                    "stream_no_prior_outputs",
                                    reason=(
                                        f"{type(exc).__name__}: "
                                        f"{str(exc)[:120]}"
                                    ),
                                )
                            if has_outputs:
                                stateful = False
                                print(
                                    "Existing output folder has no stream "
                                    "carry; continuing in rewind mode"
                                )
                                log_event("stream_legacy_rewind")
                            else:
                                carry = lfp.open_stream(start_time)
                                # persist BEFORE the first outputs: a
                                # crash mid-round-1 then still reads as a
                                # stateful folder (reconcile + resume)
                                # instead of degrading to rewind mode
                                # forever via the legacy heuristic above
                                from tpudas.proc.stream import save_carry

                                save_carry(carry, output_folder)
                    # newest timestamp from the index — no file data is
                    # read
                    contents = sub.get_contents()
                    t2 = np.datetime64(contents["time_max"].max())
                    redundant = 0.0
                    if stateful:
                        # carried state: only NEW samples are read/filtered
                        t1 = (
                            np.datetime64(int(carry.next_ingest_ns), "ns")
                            if carry.next_ingest_ns is not None
                            else start_time
                        )
                        data_sec, ch_samples = _covered_workload(
                            contents, t1, t2
                        )
                        with span(
                            "stream.round", mode="stateful", round=rnd
                        ), counters.measure(int(ch_samples), data_sec):
                            lfp.process_stream_increment(carry, t2)
                        from tpudas.proc.stream import save_carry

                        # saved AFTER the outputs: the carry is never ahead
                        # of the files (crash-only; resume reconciles the
                        # rest).  On a >1 cadence the skipped rounds keep
                        # the pytree on-device — a crash simply resumes
                        # from the last save and regenerates the tail
                        # byte-identically.
                        carry_unsaved += 1
                        if carry_unsaved >= carry_save_every:
                            save_carry(carry, output_folder)
                            carry_unsaved = 0
                    else:
                        resumed_stateful = False
                        if not rewind_wrote:
                            # a persisted carry means the folder head was
                            # written by the stateful mode; this rewind
                            # write breaks the carry's no-newer-outputs
                            # invariant, so invalidate it — and CONTINUE
                            # from the folder head (the t_last resume
                            # below) rather than reprocessing from
                            # start_time, leaving every stateful-era
                            # product file untouched
                            rewind_wrote = True
                            from tpudas.proc.stream import discard_carry

                            if discard_carry(output_folder):
                                resumed_stateful = True
                                print(
                                    "Removed stale stream carry; rewind "
                                    "mode continues from the folder head"
                                )
                        if not processed_once and not resumed_stateful:
                            t1 = start_time
                        else:
                            try:
                                t_last = lfp.get_last_processed_time()
                            except IndexError:
                                # a prior round completed without emitting
                                # output (stream still shorter than the
                                # edge trim) — no checkpoint yet, retry
                                # from the very start
                                t_last = None
                            if t_last is None:
                                t1 = start_time
                            else:
                                # rewind (ceil(edge/dt) - 1) output steps,
                                # exactly on the output grid — ns precision
                                # so fractional d_t stays seam-free (the
                                # resumed run's first emitted sample is
                                # then t_last + d_t)
                                rewind_sec = (
                                    math.ceil(edge_buffer / d_t) - 1
                                ) * d_t
                                t1 = t_last - to_timedelta64(rewind_sec)
                        data_sec, ch_samples = _covered_workload(
                            contents, t1, t2
                        )
                        if prev_t2 is not None and t1 < prev_t2:
                            # full-rate samples re-read solely to rebuild
                            # the filter's transient state (what stateful
                            # mode eliminates)
                            _, redundant = _covered_workload(
                                contents, t1, min(prev_t2, t2)
                            )
                            counters.add_redundant(int(redundant))
                        with span(
                            "stream.round", mode="rewind", round=rnd
                        ), counters.measure(int(ch_samples), data_sec):
                            lfp.process_time_range(t1, t2)
                    prev_t2 = t2
                    rounds = rnd
                    round_rt = (
                        data_sec / counters.last_wall
                        if counters.last_wall
                        else 0.0
                    )
                    mode_str = "stateful" if stateful else "rewind"
                    log_event(
                        "realtime_round",
                        round=rnd,
                        upto=str(t2),
                        mode=mode_str,
                        data_seconds=round(data_sec, 3),
                        redundant_samples=int(redundant),
                        wall_seconds=round(counters.last_wall, 4),
                        realtime_factor=round(round_rt, 2),
                        engine=lfp.parameters["engine"],
                        engine_counts=dict(lfp.engine_counts),
                        native_windows=lfp.native_windows,
                    )
                    reg.counter(
                        "tpudas_stream_rounds_total",
                        "processing rounds completed",
                        labelnames=("mode",),
                    ).inc(mode=mode_str)
                    reg.histogram(
                        "tpudas_stream_round_seconds",
                        "per-round measured processing wall time",
                    ).observe(counters.last_wall)
                    reg.gauge(
                        "tpudas_stream_realtime_factor",
                        "last round's data-seconds per wall-second",
                    ).set(round_rt)
                    reg.gauge(
                        "tpudas_stream_redundant_ratio",
                        "cumulative fraction of channel-samples re-read to "
                        "rebuild filter state",
                    ).set(counters.redundant_ratio)
                    # stateful head lag is O(1) off the carry; the rewind
                    # fallback rescans the output index, so only pay it
                    # when an operator is actually scraping health
                    head_lag = (
                        _head_lag_seconds(
                            t2, lfp, carry if stateful else None
                        )
                        if (stateful or edge_health.enabled)
                        else None
                    )
                    if head_lag is not None:
                        reg.gauge(
                            "tpudas_stream_head_lag_seconds",
                            "stream-seconds between the fiber head and the "
                            "newest emitted output",
                        ).set(head_lag)
                    if pyramid and not _resource.should_shed("pyramid"):
                        _append_pyramid(
                            output_folder, rnd, emitted_patches,
                            pyr_state,
                        )
                    if detect:
                        from tpudas.detect.runner import (
                            mark_detect_shed,
                            run_detect_round,
                        )

                        if _resource.should_shed("detect"):
                            mark_detect_shed(det_state)
                        else:
                            run_detect_round(
                                output_folder, rnd, emitted_patches,
                                det_state, operators=detect_operators,
                                step_sec=d_t,
                            )
                        edge_health.detect = det_state.get("summary")
                    boundary.on_success()
                    edge_health.write(
                        counters, rnd, polls, mode_str, round_rt, head_lag
                    )
                    reg.histogram(
                        "tpudas_stream_round_body_seconds",
                        "full processing-round wall time (index update "
                        "through health write, pyramid append included)",
                    ).observe(_time.perf_counter() - t_body)
                    if on_round is not None:
                        on_round(rnd, lfp)
                    processed_once = True
                else:
                    boundary.on_success()
                if _resource.is_degraded():
                    # disk-full recovery probe: one tiny write — the
                    # moment it succeeds, shed writers resume and the
                    # pyramid backfills from the output files
                    _resource.probe_recovery(output_folder)
                # every poll (including an empty first one) sets the
                # growth baseline: the next no-growth poll terminates
                # (reference semantics — the loop ends when the spool
                # stops growing, low_pass_dascore_edge.ipynb:205-207)
                len_last = n_now
            except Exception as exc:
                decision = boundary.on_failure(exc)
                if decision.propagate:
                    raise
                # crash-equivalent retry: drop the in-memory carry and
                # re-resolve it from disk on the next attempt — the
                # resume path reconciles any partial outputs exactly as
                # a process restart would, so a retried round and a
                # crash-restart are the same code path
                if stateful:
                    carry = None
                    carry_checked = False
                    carry_unsaved = 0
                pyr_state["store"] = None
                det_state["pipe"] = None
                edge_health.write(
                    counters, rounds, polls,
                    "stateful" if stateful else "rewind", 0.0, None,
                )
                if max_rounds is not None and polls >= max_rounds:
                    break
                with span(
                    "stream.retry",
                    kind=decision.kind,
                    attempt=boundary.consecutive,
                ):
                    sleep_fn(decision.delay)
                continue
            if max_rounds is not None and polls >= max_rounds:
                break
            sleep_fn(interval)
    except Exception as exc:
        # terminal failure: the LAST health snapshot an operator sees
        # must say why the stream died (the process is about to exit)
        edge_health.last_error = f"{type(exc).__name__}: {str(exc)[:300]}"
        get_registry().counter(
            "tpudas_stream_errors_total",
            "realtime driver crashes (recorded in health.json)",
        ).inc()
        edge_health.write(
            counters, rounds, polls,
            "stateful" if stateful else "rewind", 0.0, None,
        )
        raise
    # clean termination: flush a deferred carry save (cadence > 1) so
    # the next process resumes from the true head instead of replaying
    # the last few rounds — crash paths skip this on purpose (a
    # mid-increment carry may be ahead of the written outputs)
    if stateful and carry is not None and carry_unsaved:
        from tpudas.proc.stream import save_carry

        save_carry(carry, output_folder)
        carry_unsaved = 0
    # final snapshot on clean termination: quarantine/degradation state
    # from the LAST poll (a file can be quarantined by the very poll
    # that terminates the loop) must be visible to the operator
    edge_health.write(
        counters, rounds, polls,
        "stateful" if stateful else "rewind", round_rt, head_lag,
    )
    return rounds


# fresh patches processed per batched-rolling chunk: bounds the host
# stack (a first poll over a large pre-existing archive makes EVERY
# file fresh at once) while still amortizing the batched dispatch
_ROLLING_BATCH_CHUNK = 32


def run_rolling_realtime(
    source,
    output_folder,
    window,
    step,
    scale=1.0,
    distance=None,
    poll_interval=None,
    file_duration=30.0,
    max_rounds=None,
    sleep_fn=_time.sleep,
    engine=None,
    mesh=None,
    fault_policy=None,
    quarantine=True,
    pyramid=None,
    detect=None,
    detect_operators=None,
):
    """Poll ``source`` and rolling-mean each NEW patch (stateless per
    file — rolling_mean_dascore_edge.ipynb:209-221). Returns rounds
    that processed data.

    ``mesh`` (a :class:`jax.sharding.Mesh`, an int device count, or
    ``TPUDAS_MESH=N`` from the environment — see
    :func:`tpudas.parallel.mesh.resolve_mesh`) batches each round's
    fresh patches over the mesh's ``ch``
    axis (pure data parallelism, no collectives) in bounded chunks,
    whenever the chunk is shape-uniform and ``engine`` is not a host
    engine ("numpy"/"host" forces the per-patch host path);
    non-uniform chunks fall back to the per-patch device path.

    Rounds run inside the same per-round fault boundary as
    :func:`run_lowpass_realtime` (``fault_policy`` /
    ``quarantine`` — see RESILIENCE.md): transient/corrupt failures
    are retried with backoff, repeat-offender files quarantined.
    Patches written before a mid-round failure are in the ``processed``
    set already, so a retry resumes at the first unwritten patch.

    Driver parity with :func:`run_lowpass_realtime`: each round's
    output patches are captured in memory at their write site and fed
    to the same per-round append hooks — ``pyramid`` (default
    ``TPUDAS_PYRAMID=1``) keeps the :mod:`tpudas.serve.tiles` pyramid
    current over the rolling output, and ``detect`` (default
    ``TPUDAS_DETECT=1``, operators via ``detect_operators``) runs the
    :mod:`tpudas.detect` streaming operators over it.  Both hooks are
    crash-only, shed under disk pressure, and swallowed on failure.
    Note the rolling grid is anchored per file: for a globally uniform
    grid (what the pyramid and detect consumers assume) use a ``step``
    that divides the file duration.
    """
    import os

    from tpudas.core import units as _units
    from tpudas.parallel.mesh import resolve_mesh

    mesh = resolve_mesh(mesh)
    if mesh is not None and "ch" not in mesh.shape:
        raise ValueError(
            "run_rolling_realtime mesh needs a 'ch' axis (use "
            "tpudas.parallel.mesh.make_mesh); got axes "
            f"{tuple(mesh.shape)}"
        )
    os.makedirs(output_folder, exist_ok=True)
    _startup_audit(output_folder)
    from tpudas.integrity import resource as _resource

    interval = float(poll_interval) if poll_interval is not None else float(
        file_duration
    )
    policy = fault_policy if fault_policy is not None else RetryPolicy()
    ledger = QuarantineLedger(output_folder) if quarantine else None
    boundary = FaultBoundary(policy, ledger)
    if pyramid is None:
        pyramid = os.environ.get("TPUDAS_PYRAMID", "0") == "1"
    pyramid = bool(pyramid)
    if detect is None:
        detect = os.environ.get("TPUDAS_DETECT", "0") == "1"
    detect = bool(detect)
    step_sec = _units.get_seconds(step)
    pyr_state = {"store": None}  # cross-round open tile store (pyramid)
    det_state = {"pipe": None}  # cross-round detect pipeline (detect)
    initial_run = True
    rounds = 0
    polls = 0
    # identify patches by their time span so a late-arriving file with
    # an earlier timestamp is still processed (a positional high-water
    # mark into the time-sorted spool would skip it silently)
    processed: set = set()
    while True:
        polls += 1
        try:
            fault_point("round.body", poll=polls)
            sp = boundary.begin_round(
                make_spool(source).sort("time"), source
            )
            sub = (
                sp.select(distance=distance) if distance is not None else sp
            )
            contents = sub.get_contents()
            keys = [
                (np.datetime64(a, "ns"), np.datetime64(b, "ns"))
                for a, b in zip(contents["time_min"], contents["time_max"])
            ]
            fresh = [j for j, k in enumerate(keys) if k not in processed]
            if not initial_run and not fresh and boundary.consecutive == 0:
                print("No new data was detected. Real-time data processing ended successfully.")
                break
            if fresh:
                rnd = rounds + 1
                print("run number: ", rnd)
                emitted_patches = []  # in-memory capture (pyramid/detect)

                def write_out(j, out):
                    out = out.new(data=np.asarray(out.data) * scale)
                    fname = get_filename(
                        out.attrs["time_min"], out.attrs["time_max"]
                    )
                    out.io.write(
                        os.path.join(output_folder, fname), "dasdae"
                    )
                    processed.add(keys[j])
                    if pyramid or detect:
                        emitted_patches.append(out)

                # bounded chunks: memory stays O(chunk), outputs are
                # written as soon as they are computed
                for c0 in range(0, len(fresh), _ROLLING_BATCH_CHUNK):
                    chunk = fresh[c0 : c0 + _ROLLING_BATCH_CHUNK]
                    outs = None
                    if (
                        mesh is not None
                        and engine not in ("numpy", "host")
                        and len(chunk) > 1
                    ):
                        from tpudas.ops.rolling import (
                            rolling_mean_patches_batched,
                        )

                        patches = [sub[j] for j in chunk]
                        outs = rolling_mean_patches_batched(
                            mesh, patches, window, step
                        )
                        if outs is not None:
                            log_event(
                                "rolling_batched",
                                patches=len(chunk),
                                mesh=dict(mesh.shape),
                            )
                            for j, out in zip(chunk, outs):
                                write_out(j, out)
                    if outs is None:
                        for j in chunk:
                            print("working on patch ", j)
                            write_out(
                                j,
                                sub[j]
                                .rolling(
                                    time=window, step=step, engine=engine
                                )
                                .mean(),
                            )
                # driver parity with run_lowpass_realtime: the same
                # per-round serve/detect append hooks over the same
                # in-memory emit capture
                if pyramid and not _resource.should_shed("pyramid"):
                    _append_pyramid(
                        output_folder, rnd, emitted_patches, pyr_state
                    )
                if detect:
                    from tpudas.detect.runner import (
                        mark_detect_shed,
                        run_detect_round,
                    )

                    if _resource.should_shed("detect"):
                        mark_detect_shed(det_state)
                    else:
                        run_detect_round(
                            output_folder, rnd, emitted_patches,
                            det_state, operators=detect_operators,
                            step_sec=step_sec,
                        )
                rounds = rnd
            boundary.on_success()
            if _resource.is_degraded():
                _resource.probe_recovery(output_folder)
            initial_run = False
        except Exception as exc:
            pyr_state["store"] = None
            det_state["pipe"] = None
            decision = boundary.on_failure(exc)
            if decision.propagate:
                raise
            if max_rounds is not None and polls >= max_rounds:
                break
            with span(
                "stream.retry",
                kind=decision.kind,
                attempt=boundary.consecutive,
            ):
                sleep_fn(decision.delay)
            continue
        if max_rounds is not None and polls >= max_rounds:
            break
        sleep_fn(interval)
    return rounds
