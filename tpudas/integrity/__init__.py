"""tpudas.integrity: checksummed persistent state, startup
audit/repair, and disk-full graceful degradation.

The paper's product is the on-disk state next to the interrogator —
stream carry, quarantine ledger, tile pyramid, health snapshots — and
nobody is around to notice bit rot, torn writes after power loss, or a
filling disk.  PR 3 made in-process faults survivable and PR 4 made
artifacts crash-only by ordering; this package makes corruption
**detectable** and **repairable**:

- :mod:`tpudas.integrity.checksum` — crc32 stamping (embedded for
  JSON, ``.crc`` sidecar for binary) and the verified-read helpers
  every durable artifact now goes through.  A rejected primary falls
  down a degradation ladder — ``.prev`` double buffer →
  rebuild-from-outputs → rewind — each step counted
  (``tpudas_integrity_fallback_total``) and surfaced in
  ``health.json``;
- :mod:`tpudas.integrity.audit` — the startup "fsck": scans every
  artifact, classifies (ok / unstamped / torn / corrupt / stale-tmp /
  orphan tile), repairs what it can, and runs automatically before the
  realtime drivers' first round (``tools/fsck.py`` is the operator
  CLI);
- :mod:`tpudas.integrity.resource` — ``ENOSPC``/``EDQUOT`` graceful
  degradation: shed non-essential writers (pyramid, metrics.prom)
  while the core stream + carry stay alive, recover automatically when
  a probe write succeeds.

See RESILIENCE.md ("Integrity & recovery") for formats, the ladder,
and the fsck / crash-drill runbook.
"""

from tpudas.integrity.audit import audit, audit_backfill, audit_fleet
from tpudas.integrity.checksum import (
    CRC_KEY,
    SIDECAR_SUFFIX,
    crc32_hex,
    fallback_count,
    stamp_json,
    verify_file_checksum,
    verify_json_obj,
    write_json_checksummed,
    write_sidecar_for,
)
from tpudas.integrity.resource import (
    RESOURCE_ERRNOS,
    is_degraded,
    is_resource_error,
    note_pressure,
    probe_recovery,
    should_shed,
)

__all__ = [
    "CRC_KEY",
    "RESOURCE_ERRNOS",
    "SIDECAR_SUFFIX",
    "audit",
    "audit_backfill",
    "audit_fleet",
    "crc32_hex",
    "fallback_count",
    "is_degraded",
    "is_resource_error",
    "note_pressure",
    "probe_recovery",
    "should_shed",
    "stamp_json",
    "verify_file_checksum",
    "verify_json_obj",
    "write_json_checksummed",
    "write_sidecar_for",
]
