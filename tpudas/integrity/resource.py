"""Disk-full (``ENOSPC``/``EDQUOT``) graceful degradation.

An edge box whose disk fills must not die — and must not spend its
remaining breath failing to write metrics.  This module keeps one
process-wide pressure flag:

- any writer that hits a resource error **notes pressure**
  (:func:`note_pressure`): the flag flips, the
  ``tpudas_integrity_resource_degraded`` gauge goes to 1, and the
  realtime driver starts **shedding non-essential writers** — the
  pyramid append, ``metrics.prom`` — via :func:`should_shed` (each
  shed is counted per writer in
  ``tpudas_integrity_writes_shed_total``).  The core stream, the
  carry, and ``health.json`` (the operator's only window into the
  degradation) keep going; a carry save that fails on ENOSPC is
  retried by the fault boundary under the ``"resource"`` kind with
  extra patience (``RetryPolicy.resource_patience``).
- every round-end while degraded, the driver calls
  :func:`probe_recovery`: a tiny probe write into the output folder.
  The moment it succeeds the flag clears, shed writers resume, and the
  pyramid's next ``sync`` backfills whatever the shed rounds skipped —
  recovery is automatic, no operator action.

The probe goes through the same ``fs.write_enospc`` fault site as
every real write (tpudas.utils.atomicio), so the whole degrade/recover
cycle is deterministically drillable from a :class:`FaultPlan`.
"""

from __future__ import annotations

import os
import time

from tpudas.obs.registry import get_registry
# the taxonomy (classify_failure) owns the errno set; one definition
# so a new resource errno cannot split retry and shedding behavior
from tpudas.resilience.faults import RESOURCE_ERRNOS
from tpudas.utils.logging import log_event

__all__ = [
    "RESOURCE_ERRNOS",
    "clear_pressure",
    "is_degraded",
    "is_resource_error",
    "note_pressure",
    "probe_recovery",
    "should_shed",
]

_PROBE_FILENAME = ".space_probe.tmp"  # .tmp: the audit sweeps leftovers

_STATE = {"degraded": False, "since": None, "last_error": None}


def is_resource_error(exc: BaseException, _depth: int = 4) -> bool:
    """True when ``exc`` (or a cause within 4 links) is a disk-full /
    quota OSError."""
    while exc is not None and _depth > 0:
        if (
            isinstance(exc, OSError)
            and getattr(exc, "errno", None) in RESOURCE_ERRNOS
        ):
            return True
        exc = exc.__cause__ or exc.__context__
        _depth -= 1
    return False


def is_degraded() -> bool:
    return _STATE["degraded"]


def note_pressure(where: str, exc: BaseException | None = None) -> None:
    """Flip (or refresh) the resource-pressure flag after a writer hit
    ENOSPC/EDQUOT at ``where``."""
    err = None if exc is None else f"{type(exc).__name__}: {str(exc)[:200]}"
    _STATE["last_error"] = err
    if _STATE["degraded"]:
        return
    _STATE["degraded"] = True
    _STATE["since"] = time.time()
    reg = get_registry()
    reg.counter(
        "tpudas_integrity_resource_events_total",
        "disk-full/quota pressure episodes (flag flips to degraded)",
    ).inc()
    reg.gauge(
        "tpudas_integrity_resource_degraded",
        "1 while non-essential writers are shed for disk-full/quota "
        "pressure",
    ).set(1.0)
    log_event("resource_pressure", where=where, error=err)


def clear_pressure(reason: str = "") -> None:
    if not _STATE["degraded"]:
        return
    _STATE["degraded"] = False
    _STATE["since"] = None
    _STATE["last_error"] = None
    get_registry().gauge(
        "tpudas_integrity_resource_degraded",
        "1 while non-essential writers are shed for disk-full/quota "
        "pressure",
    ).set(0.0)
    log_event("resource_recovered", reason=reason)


def should_shed(writer: str) -> bool:
    """True while resource-degraded — and counts the shed per writer,
    so skipped pyramid/prom rounds are visible, never silent."""
    if not _STATE["degraded"]:
        return False
    get_registry().counter(
        "tpudas_integrity_writes_shed_total",
        "non-essential writes skipped under disk-full/quota pressure",
        labelnames=("writer",),
    ).inc(writer=writer)
    return True


def probe_recovery(folder: str) -> bool:
    """While degraded, try one tiny write into ``folder``; on success
    clear the pressure flag (shed writers resume next round).  Returns
    True when not (or no longer) degraded."""
    if not _STATE["degraded"]:
        return True
    probe = os.path.join(str(folder), _PROBE_FILENAME)
    try:
        from tpudas.resilience.faults import fault_point

        fault_point("fs.write_enospc", path=probe)
        with open(probe, "w") as fh:
            fh.write("x" * 512)
        os.remove(probe)
    except OSError as exc:
        _STATE["last_error"] = f"{type(exc).__name__}: {str(exc)[:200]}"
        log_event("resource_probe_failed", error=_STATE["last_error"])
        return False
    clear_pressure("probe write succeeded")
    return True
