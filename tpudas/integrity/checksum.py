"""crc32 stamping and verification for every durable tpudas artifact.

Two formats, chosen by what the artifact already is:

- **JSON artifacts** (health.json, quarantine ledger, pyramid
  manifest, directory-index cache, carry sidecar) embed the digest as
  a top-level ``"_crc32"`` key computed over the **canonical** dump of
  the rest of the object (sorted keys, no whitespace).  The stamp
  survives any JSON re-serialization, costs no extra file, and readers
  that don't verify simply see one extra key.
- **Binary artifacts** (the carry ``.npz``, pyramid tiles and
  ``tails.npy``) get a sidecar ``<path>.crc`` holding
  ``crc32 <8-hex-digest> <size>\\n``.  The sidecar is written *after*
  the payload rename, so a crash between the two leaves a stale
  sidecar — verification fails, the reader takes its ladder, and the
  startup audit re-stamps the (still internally consistent, because
  the rename was atomic) payload.

A verification result is one of three strings: ``"ok"``,
``"unstamped"`` (a legacy artifact from before this module — accepted,
counted), or ``"mismatch"`` (bit rot / torn copy — the reader must
fall through its degradation ladder, never trust the bytes).

Every ladder step a reader takes is counted in
``tpudas_integrity_fallback_total{artifact=...}`` AND in a process
counter (:func:`fallback_count`) the realtime driver snapshots into
``health.json`` (``integrity_fallbacks``/``degraded``), so recovery is
never silent.  Verification funnels through the ``integrity.verify``
fault-injection site, so a test can deterministically corrupt (action
``"truncate"``) any artifact just before its verified read.
"""

from __future__ import annotations

import io
import json
import os
import zlib

from tpudas.obs.registry import get_registry
from tpudas.utils.atomicio import (
    atomic_write_bytes,
    atomic_write_text,
)
from tpudas.utils.logging import log_event

__all__ = [
    "CRC_KEY",
    "SIDECAR_SUFFIX",
    "count_fallback",
    "count_unstamped",
    "crc32_hex",
    "fallback_count",
    "read_json_verified",
    "rotate_prev",
    "sidecar_path",
    "stamp_json",
    "strip_stamp",
    "verify_file_checksum",
    "verify_json_obj",
    "write_bytes_checksummed",
    "write_json_checksummed",
    "write_npy_checksummed",
    "write_sidecar_for",
]

CRC_KEY = "_crc32"
SIDECAR_SUFFIX = ".crc"


def crc32_hex(data: bytes) -> str:
    return format(zlib.crc32(data) & 0xFFFFFFFF, "08x")


def _canonical(obj) -> bytes:
    """The byte string the JSON stamp digests: sorted keys, minimal
    separators — identical before the write and after any parse, so
    the stamp survives re-serialization and pretty-printing."""
    return json.dumps(
        obj, sort_keys=True, separators=(",", ":"), default=str
    ).encode()


# ---------------------------------------------------------------------------
# fallback accounting (what health.json's `integrity_fallbacks` reads)

_fallbacks = 0  # process-lifetime ladder steps (all artifacts)


def fallback_count() -> int:
    """Verified reads (process lifetime) that rejected a primary and
    took a degradation-ladder step.  The realtime driver snapshots a
    per-run delta of this into ``health.json``."""
    return _fallbacks


def count_fallback(artifact: str, reason: str, path: str = "") -> None:
    """One degradation-ladder step: the primary for ``artifact`` was
    rejected (checksum mismatch, parse failure, version skew) and the
    reader is falling through to ``.prev`` / rebuild / rewind."""
    global _fallbacks
    _fallbacks += 1
    get_registry().counter(
        "tpudas_integrity_fallback_total",
        "verified reads that rejected the primary artifact and took a "
        "degradation-ladder step (.prev / rebuild / rewind)",
        labelnames=("artifact",),
    ).inc(artifact=artifact)
    log_event(
        "integrity_fallback",
        artifact=artifact,
        reason=str(reason)[:200],
        path=str(path),
    )


def count_unstamped(artifact: str) -> None:
    """A legacy artifact without a checksum was accepted (visibility
    only — the audit re-stamps these)."""
    get_registry().counter(
        "tpudas_integrity_unstamped_total",
        "checksum-less legacy artifacts accepted by verified reads "
        "(the startup audit re-stamps them)",
        labelnames=("artifact",),
    ).inc(artifact=artifact)


def _verify_point(path: str, artifact: str | None) -> None:
    from tpudas.resilience.faults import fault_point

    fault_point("integrity.verify", path=path, artifact=artifact)


# ---------------------------------------------------------------------------
# embedded-digest JSON

def stamp_json(obj: dict) -> dict:
    """``obj`` plus a ``"_crc32"`` key digesting the canonical dump of
    everything else (an existing stamp is replaced)."""
    body = {k: v for k, v in obj.items() if k != CRC_KEY}
    return {**body, CRC_KEY: crc32_hex(_canonical(body))}


def verify_json_obj(obj) -> str:
    """``"ok"`` | ``"unstamped"`` | ``"mismatch"`` for a parsed JSON
    object."""
    if not isinstance(obj, dict) or CRC_KEY not in obj:
        return "unstamped"
    body = {k: v for k, v in obj.items() if k != CRC_KEY}
    stamp = obj[CRC_KEY]
    return "ok" if crc32_hex(_canonical(body)) == stamp else "mismatch"


def strip_stamp(obj: dict) -> dict:
    return {k: v for k, v in obj.items() if k != CRC_KEY}


def write_json_checksummed(
    path: str, obj: dict, durable: bool | None = None, indent: int = 1
) -> None:
    """Atomically write ``obj`` with an embedded crc32 stamp."""
    atomic_write_text(
        path, json.dumps(stamp_json(obj), indent=indent) + "\n",
        durable=durable,
    )


def read_json_verified(path: str, artifact: str) -> tuple[dict, str]:
    """Parse + verify one JSON artifact: ``(payload_without_stamp,
    status)``.  Raises whatever ``open``/``json.load`` raises (the
    caller's ladder handles unreadable exactly like mismatched);
    ``status`` is ``"ok"``/``"unstamped"``/``"mismatch"``.  The
    payload is returned even on mismatch so a caller that *chooses* to
    limp on (none do today) could."""
    _verify_point(path, artifact)
    with open(path) as fh:
        obj = json.load(fh)
    status = verify_json_obj(obj)
    return (strip_stamp(obj) if isinstance(obj, dict) else obj), status


# ---------------------------------------------------------------------------
# sidecar-digest binary

def sidecar_path(path: str) -> str:
    return path + SIDECAR_SUFFIX


def write_bytes_checksummed(
    path: str, payload: bytes, durable: bool | None = None
) -> None:
    """Atomic payload write + ``<path>.crc`` sidecar (payload first —
    a crash between the two reads as "mismatch" and the audit
    re-stamps)."""
    atomic_write_bytes(path, payload, durable=durable)
    atomic_write_text(
        sidecar_path(path),
        f"crc32 {crc32_hex(payload)} {len(payload)}\n",
        durable=durable,
    )


def write_npy_checksummed(path: str, array, durable: bool | None = None) -> (
    None
):
    """Checksummed atomic raw ``.npy`` write (serialized in memory so
    the sidecar digests exactly the bytes on disk)."""
    import numpy as np

    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(array))
    write_bytes_checksummed(path, buf.getvalue(), durable=durable)


def write_sidecar_for(path: str, durable: bool | None = None) -> str:
    """(Re-)stamp an existing binary artifact from its current bytes —
    the audit's repair for unstamped/stale-sidecar payloads.  Returns
    the digest."""
    with open(path, "rb") as fh:
        payload = fh.read()
    digest = crc32_hex(payload)
    atomic_write_text(
        sidecar_path(path), f"crc32 {digest} {len(payload)}\n",
        durable=durable,
    )
    return digest


def verify_file_checksum(path: str, artifact: str | None = None) -> str:
    """``"ok"`` | ``"unstamped"`` | ``"mismatch"`` for a binary
    artifact against its ``.crc`` sidecar.  Missing payload raises
    ``FileNotFoundError`` (absence is the caller's decision, not a
    checksum state)."""
    _verify_point(path, artifact)
    side = sidecar_path(path)
    try:
        with open(side) as fh:
            tokens = fh.read().split()
    except FileNotFoundError:
        if not os.path.isfile(path):
            raise FileNotFoundError(path)
        return "unstamped"
    with open(path, "rb") as fh:
        payload = fh.read()
    if (
        len(tokens) != 3
        or tokens[0] != "crc32"
        or not tokens[2].isdigit()
    ):
        return "mismatch"
    if int(tokens[2]) != len(payload) or tokens[1] != crc32_hex(payload):
        return "mismatch"
    return "ok"


# ---------------------------------------------------------------------------
# .prev rotation (payload + sidecar move together)

def rotate_prev(path: str) -> bool:
    """Rotate ``path`` (and its ``.crc`` sidecar, if any) to
    ``path.prev`` / ``path.prev.crc`` — the double-buffer step before
    writing a new primary.  Returns True when a primary existed."""
    if not os.path.isfile(path):
        return False
    os.replace(path, path + ".prev")
    side = sidecar_path(path)
    if os.path.isfile(side):
        os.replace(side, sidecar_path(path + ".prev"))
    return True
