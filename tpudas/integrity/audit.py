"""Startup audit / repair ("fsck") for one output folder's durable state.

The realtime drivers call :func:`audit` once at startup — before the
first round, before the quarantine ledger loads — and
``tools/fsck.py`` exposes it as an operator CLI.  It scans every
durable artifact the pipeline writes beside the stream:

==================  =====================================================
artifact            files
==================  =====================================================
``carry``           ``.stream_carry.npz`` (+ ``.crc``/``.prev``) and the
                    ``.stream_carry.json`` sidecar
``quarantine``      ``.quarantine.json`` (+ ``.prev``)
``health``          ``health.json`` (+ ``.prev``)
``index``           ``.tpudas_index.json`` (+ ``.prev``)
``pyramid``         ``.tiles/manifest.json`` (+ ``.prev``),
                    ``.tiles/tails.npy``, ``.tiles/L*/NNNNNNNN.npy``
                    and compressed ``.tiles/L*/NNNNNNNN.tpt`` blobs
                    (verified via their embedded crc32 — ISSUE 11)
``detect_carry``    ``.detect/carry.npz`` (+ ``.crc``/``.prev``)
``events``          ``.detect/events.jsonl`` (+ ``.prev``) — per-line
                    crc32 stamps, contiguous ``seq``
``scores``          ``.detect/scores/manifest.json`` (+ ``.prev``),
                    ``.detect/scores/tails.npy``,
                    ``.detect/scores/NNNNNNNN.npy``
``flight``          ``.flight/seg-NNNNNNNN.jsonl`` — the crash-surviving
                    flight recorder's segments (per-line crc32 stamps;
                    a SIGKILL-torn tail is truncated to the verified
                    prefix — ISSUE 13)
``tmp``             any ``*.tmp`` / ``*.tmp.<pid>`` leftover anywhere in
                    the tree (a crashed writer's half file)
==================  =====================================================

and classifies each as ``ok`` (not reported), ``unstamped`` (legacy,
no checksum yet), ``torn`` (crc32 mismatch — a torn/partial write or
bit rot), ``corrupt`` (does not even parse / internally inconsistent),
``stale_tmp``, or ``orphan`` (a tile beyond the manifest head that
also fails verification).  With ``repair=True`` (the default) it then
fixes what it can, in artifact-appropriate ways:

- stale tmp files are **removed** (regenerable by construction);
- unstamped-but-parseable artifacts are **restamped** in place;
- a bad primary with a good ``.prev`` is **promoted** (the ladder's
  runtime fallback, made durable);
- a bad primary with no good ``.prev`` is **removed** — every reader
  treats absence safely (carry → rewind, ledger → empty, health →
  regenerated next round, index → rescan);
- a bad in-use pyramid artifact triggers a **rebuild** of ``.tiles/``
  from the output files (byte-identical, the store is derived data);
- detect artifacts follow the same ladder with their own last rung: a
  ledger/scores surplus beyond the detect carry is **truncated** back
  to the carry's commit point (the runner's resume reconcile, made
  durable), and anything unreconcilable — both ledger rungs bad, the
  carry unreadable, committed score rows missing — **resets**
  ``.detect/`` entirely: the detection history is derived data and
  recomputes deterministically from the output files
  (DETECTION.md, "Failure model").

Run the CLI only while the driver is stopped (the tmp sweep cannot
tell a crashed writer's leftovers from a live writer's in-flight
file); the driver's own startup call cannot race anything because its
writers have not started.

A second audit immediately after a repairing one reports ``clean``
with zero issues — the crash-drill (tools/crash_drill.py) asserts
exactly that after every SIGKILL.

Fleets (ISSUE 8): a :class:`tpudas.fleet.FleetEngine` root holds one
output folder per stream (``root/<stream_id>/``).  :func:`audit_fleet`
runs the same audit over every stream root and aggregates the
reports; ``tools/fsck.py --fleet`` and ``tools/crash_drill.py
--streams N`` drive it.  Each stream is classified and repaired
independently — one stream's damage never touches another's state.
"""

from __future__ import annotations

import os
import re
import time

from tpudas.integrity.checksum import (
    read_json_verified,
    sidecar_path,
    verify_file_checksum,
    write_json_checksummed,
    write_sidecar_for,
)
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.utils.atomicio import is_tmp_name
from tpudas.utils.logging import log_event

__all__ = [
    "audit",
    "audit_backfill",
    "audit_backfill_store",
    "audit_fleet",
    "fleet_stream_dirs",
]

_TILE_NAME_RE = re.compile(r"^(\d{8})\.npy$")
# compressed pyramid tiles (tpudas.codec blobs, ISSUE 11): the crc is
# embedded in the container, so verification reads the file alone
_TILE_BLOB_NAME_RE = re.compile(r"^(\d{8})\.tpt$")


def _issue(issues, artifact, path, status, action, detail=""):
    issues.append(
        {
            "artifact": artifact,
            "path": str(path),
            "status": status,
            "action": action,
            "detail": str(detail)[:200],
        }
    )


def _repair_action(repair: bool, action: str) -> str:
    return action if repair else "found"


def _promote_prev(path: str) -> None:
    """Replace a bad primary with its good ``.prev`` (sidecar
    included)."""
    for p in (path, sidecar_path(path)):
        if os.path.isfile(p):
            os.remove(p)
    os.replace(path + ".prev", path)
    prev_side = sidecar_path(path + ".prev")
    if os.path.isfile(prev_side):
        os.replace(prev_side, sidecar_path(path))


def _remove_all(*paths) -> None:
    for p in paths:
        if os.path.isfile(p):
            os.remove(p)


# ---------------------------------------------------------------------------
# per-artifact checks

def _sweep_tmp(folder: str, issues: list, repair: bool) -> None:
    for dirpath, _dirnames, filenames in os.walk(folder):
        for name in sorted(filenames):
            if not is_tmp_name(name):
                continue
            path = os.path.join(dirpath, name)
            if repair:
                try:
                    os.remove(path)
                except OSError as exc:
                    _issue(issues, "tmp", path, "stale_tmp", "failed", exc)
                    continue
            _issue(
                issues, "tmp", path, "stale_tmp",
                _repair_action(repair, "removed"),
            )


def _json_status(path: str, artifact: str, validate=None) -> tuple:
    """(status, payload_or_None, detail): status in ok | unstamped |
    torn | corrupt | absent."""
    if not os.path.isfile(path):
        return "absent", None, ""
    try:
        payload, status = read_json_verified(path, artifact)
    except Exception as exc:
        return "corrupt", None, f"{type(exc).__name__}: {str(exc)[:120]}"
    if status == "mismatch":
        return "torn", payload, "crc32 mismatch"
    try:
        if validate is not None:
            validate(payload)
    except Exception as exc:
        return "corrupt", payload, f"{type(exc).__name__}: {str(exc)[:120]}"
    return ("unstamped" if status == "unstamped" else "ok"), payload, ""


def _check_json_artifact(
    path: str, artifact: str, issues: list, repair: bool, validate=None
) -> None:
    """The shared JSON ladder repair: restamp unstamped, promote a good
    ``.prev`` over a torn/corrupt primary, remove what nothing can
    save (absence is safe for every JSON artifact)."""
    prev = path + ".prev"
    status, payload, detail = _json_status(path, artifact, validate)
    if status == "ok":
        pass
    elif status == "absent":
        # a lone .prev is the crash window between the save's rotate
        # and write: promote a good one, remove a bad one — either
        # way the NEXT audit (and every runtime read) finds nothing
        # to fall back over
        if os.path.isfile(prev):
            p_status, p_payload, p_detail = _json_status(
                prev, artifact, validate
            )
            if p_status in ("ok", "unstamped"):
                if repair:
                    os.replace(prev, path)
                    if p_status == "unstamped":
                        write_json_checksummed(path, p_payload)
                _issue(
                    issues, artifact, prev, "torn",
                    _repair_action(repair, "promoted_prev"),
                    "orphaned .prev (primary missing)",
                )
            else:
                if repair:
                    _remove_all(prev)
                _issue(
                    issues, artifact, prev, p_status,
                    _repair_action(repair, "removed"), p_detail,
                )
        return
    elif status == "unstamped":
        if repair:
            write_json_checksummed(path, payload)
        _issue(
            issues, artifact, path, "unstamped",
            _repair_action(repair, "restamped"),
        )
    else:  # torn | corrupt
        p_status, p_payload, _ = _json_status(prev, artifact, validate)
        if p_status in ("ok", "unstamped"):
            if repair:
                os.remove(path)
                os.replace(prev, path)
                if p_status == "unstamped":
                    write_json_checksummed(path, p_payload)
            _issue(
                issues, artifact, path, status,
                _repair_action(repair, "promoted_prev"), detail,
            )
        else:
            # both rungs bad: BOTH must go, or the runtime ladder
            # keeps tripping (counted, degraded) over the corpse of
            # the .prev after a "clean" fsck
            if repair:
                _remove_all(path, prev)
            _issue(
                issues, artifact, path, status,
                _repair_action(repair, "removed"), detail,
            )
        return
    # a bad .prev behind a healthy primary is dead weight: sweep it
    if os.path.isfile(prev):
        p_status, _p, p_detail = _json_status(prev, artifact, validate)
        if p_status in ("torn", "corrupt"):
            if repair:
                _remove_all(prev)
            _issue(
                issues, artifact, prev, p_status,
                _repair_action(repair, "removed"), p_detail,
            )


def _carry_status(path: str) -> tuple:
    """(status, carry_or_None, detail) for one carry ``.npz`` rung."""
    from tpudas.proc.stream import _parse_carry

    if not os.path.isfile(path):
        return "absent", None, ""
    try:
        crc = verify_file_checksum(path, artifact="carry")
    except FileNotFoundError:
        return "absent", None, ""
    try:
        carry = _parse_carry(path)
    except Exception as exc:
        status = "torn" if crc == "mismatch" else "corrupt"
        return status, None, f"{type(exc).__name__}: {str(exc)[:120]}"
    if crc == "mismatch":
        return "torn", None, "crc32 mismatch"
    return ("unstamped" if crc == "unstamped" else "ok"), carry, ""


def _check_carry(folder: str, issues: list, repair: bool) -> None:
    from tpudas.proc.stream import CARRY_FILENAME, CARRY_SIDECAR

    path = os.path.join(folder, CARRY_FILENAME)
    side = os.path.join(folder, CARRY_SIDECAR)
    status, carry, detail = _carry_status(path)
    if status == "unstamped":
        if repair:
            write_sidecar_for(path)
        _issue(
            issues, "carry", path, "unstamped",
            _repair_action(repair, "restamped"),
        )
        status = "ok"
    if status in ("torn", "corrupt"):
        p_status, p_carry, _ = _carry_status(path + ".prev")
        if p_status in ("ok", "unstamped"):
            if repair:
                _promote_prev(path)
                if p_status == "unstamped":
                    write_sidecar_for(path)
                carry = p_carry
            _issue(
                issues, "carry", path, status,
                _repair_action(repair, "promoted_prev"), detail,
            )
        else:
            if repair:
                _remove_all(
                    path, sidecar_path(path), path + ".prev",
                    sidecar_path(path + ".prev"), side,
                )
            _issue(
                issues, "carry", path, status,
                _repair_action(repair, "removed"), detail,
            )
            return
    elif status == "absent":
        # a lone .prev is the crash window between the save's rotate
        # and write: promote a good one (the state load_carry would
        # resume from anyway), remove a bad one
        if os.path.isfile(path + ".prev"):
            p_status, p_carry, p_detail = _carry_status(path + ".prev")
            if p_status in ("ok", "unstamped"):
                if repair:
                    _promote_prev(path)
                    if p_status == "unstamped":
                        write_sidecar_for(path)
                    carry = p_carry
                _issue(
                    issues, "carry", path + ".prev", "torn",
                    _repair_action(repair, "promoted_prev"),
                    "orphaned .prev (primary missing)",
                )
                if carry is not None and repair:
                    write_json_checksummed(side, carry._meta())
                return
            if repair:
                _remove_all(
                    path + ".prev", sidecar_path(path + ".prev"), side
                )
            _issue(
                issues, "carry", path + ".prev", p_status,
                _repair_action(repair, "removed"), p_detail,
            )
            return
        # a sidecar with no carry is leftover state
        if os.path.isfile(side):
            if repair:
                _remove_all(side)
            _issue(
                issues, "carry", side, "corrupt",
                _repair_action(repair, "removed"), "sidecar without carry",
            )
        return
    # the human-readable sidecar: cosmetic, regenerable from the meta
    if carry is not None:
        s_status, _p, s_detail = _json_status(side, "carry")
        if s_status in ("torn", "corrupt", "absent", "unstamped"):
            if repair:
                write_json_checksummed(side, carry._meta())
            if s_status != "absent":
                _issue(
                    issues, "carry", side, s_status,
                    _repair_action(repair, "rewritten"), s_detail,
                )


def _parse_lfdas_t0(name: str):
    """ns int of the start time encoded in an ``LFDAS_<t0>_<t1>.h5``
    output name (tpudas.proc.naming), or None."""
    import numpy as np

    try:
        stem = name.split("_")[1]
        date, tod = stem.split("T")
        iso = f"{date}T{tod[0:2]}:{tod[2:4]}:{tod[4:]}"
        return int(
            np.datetime64(iso).astype("datetime64[ns]").astype(np.int64)
        )
    except Exception:
        return None


def _check_outputs(folder: str, issues: list, repair: bool) -> None:
    """Sweep torn OUTPUT files a SIGKILL left mid-HDF5-write.  Scoped
    to files strictly newer than the carry's last emitted sample: those
    are exactly the ones the stateful resume regenerates byte-identically
    (the same rule :func:`tpudas.proc.stream.reconcile_outputs` applies
    — but reconcile only sees files that SCAN, and a torn file does
    not, so it would linger as unreadable garbage forever).  Without a
    carry nothing is provably regenerable, so nothing is touched."""
    from tpudas.io.registry import scan_file
    from tpudas.proc.stream import CARRY_FILENAME

    status, carry, _ = _carry_status(os.path.join(folder, CARRY_FILENAME))
    if status != "ok" or carry is None:
        return
    cutoff = carry.last_emit_ns  # None = nothing emitted: all stale
    for name in sorted(os.listdir(folder)):
        if not (name.startswith("LFDAS_") and name.endswith(".h5")):
            continue
        t0 = _parse_lfdas_t0(name)
        if t0 is None or (cutoff is not None and t0 <= cutoff):
            continue
        path = os.path.join(folder, name)
        try:
            scan_file(path, format="dasdae")
            continue  # readable: reconcile_outputs owns it
        except Exception as exc:
            detail = f"{type(exc).__name__}: {str(exc)[:120]}"
        if repair:
            _remove_all(path)
        _issue(
            issues, "output", path, "torn",
            _repair_action(repair, "removed"), detail,
        )


def _tile_blob_status(path: str) -> str:
    """``ok`` | ``torn`` | ``corrupt`` | ``absent`` for one
    compressed tile blob, via its embedded crc plus a full decode (a
    blob whose payload verifies but whose codec params cannot
    reproduce the declared geometry is corrupt, not ok)."""
    from tpudas.codec import decode_tile, verify_tile_blob

    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except FileNotFoundError:
        return "absent"
    except OSError:
        return "corrupt"
    status = verify_tile_blob(blob)
    if status != "ok":
        return status
    try:
        decode_tile(blob)
    except Exception:
        return "corrupt"
    return "ok"


def _raw_manifest_geometry(manifest: str) -> tuple:
    """(factor, tile_len, codec) from whichever manifest rung still
    parses — a checksum-IGNORED read, used only to preserve the
    pyramid geometry (and tile codec, ISSUE 11) across a rebuild.
    (None, None, None) when nothing parses; ``codec`` is the
    ``(id_or_None, params)`` pair :func:`rebuild_pyramid` accepts."""
    import json

    for path in (manifest, manifest + ".prev"):
        try:
            with open(path) as fh:
                raw = json.load(fh)
            codec = (
                raw.get("codec") or None,
                dict(raw.get("codec_params") or {}),
            )
            return int(raw["factor"]), int(raw["tile_len"]), codec
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return None, None, None


def _tile_in_use(store, level: int, tile_idx: int) -> bool:
    """Whether the read path can reference this tile: within the
    manifest head, or the head tile itself (a crashed-future complete
    file there legitimately serves the partial rows)."""
    if store is None or level >= len(store.levels):
        return False
    return tile_idx <= store.n(level) // store.tile_len


def _check_pyramid(
    folder: str, issues: list, repair: bool, rebuild: bool
) -> None:
    from tpudas.serve.tiles import (
        MANIFEST_FILENAME,
        TILE_DIRNAME,
        TileStore,
        rebuild_pyramid,
    )

    tiles_dir = os.path.join(folder, TILE_DIRNAME)
    if not os.path.isdir(tiles_dir):
        return
    manifest = os.path.join(tiles_dir, MANIFEST_FILENAME)
    # capture rebuild inputs BEFORE the JSON repair can delete the
    # rungs: whether any manifest existed at all (a store that fails
    # to open afterwards then still rebuilds instead of stranding its
    # tiles), and the geometry from whichever rung still parses
    # (checksum-ignored — factor/tile_len must survive the rebuild or
    # the byte-identical claim breaks)
    had_manifest = os.path.isfile(manifest) or os.path.isfile(
        manifest + ".prev"
    )
    geom_factor, geom_tile_len, geom_codec = _raw_manifest_geometry(
        manifest
    )
    _check_json_artifact(manifest, "manifest", issues, repair)
    store = TileStore.open(folder)
    need_rebuild = False
    if store is None:
        if had_manifest:
            need_rebuild = True
            _issue(
                issues, "manifest", manifest, "corrupt",
                "pending_rebuild", "no loadable manifest rung",
            )
    else:
        # tails: restamp a legacy checksum-less file, then one
        # verified parse (the partial rows of every level)
        tails_path = store.tails_path
        if os.path.isfile(tails_path):
            try:
                crc = verify_file_checksum(tails_path, artifact="tails")
            except FileNotFoundError:
                crc = None
            if crc == "unstamped":
                if repair:
                    write_sidecar_for(tails_path)
                _issue(
                    issues, "tails", tails_path, "unstamped",
                    _repair_action(repair, "restamped"),
                )
        try:
            store._load_tails()
        except Exception as exc:
            need_rebuild = True
            log_event(
                "integrity_tails_unreadable",
                path=store.tails_path,
                error=f"{type(exc).__name__}: {str(exc)[:120]}",
            )
            _issue(
                issues, "tails", store.tails_path, "torn",
                "pending_rebuild",
                f"{type(exc).__name__}: {str(exc)[:120]}",
            )
    # every tile file: verify; restamp legacy, classify bad ones
    for level_name in sorted(os.listdir(tiles_dir)):
        if not level_name.startswith("L"):
            continue
        level_dir = os.path.join(tiles_dir, level_name)
        if not os.path.isdir(level_dir):
            continue
        try:
            level = int(level_name[1:])
        except ValueError:
            continue
        for name in sorted(os.listdir(level_dir)):
            m = _TILE_NAME_RE.match(name)
            mb = _TILE_BLOB_NAME_RE.match(name)
            if m is None and mb is None:
                continue
            tile_idx = int((m or mb).group(1))
            path = os.path.join(level_dir, name)
            if mb is not None:
                # compressed tile: the container's embedded crc32 is
                # the stamp — never "unstamped", a blob either
                # verifies or takes the ladder
                status = _tile_blob_status(path)
                if status in ("ok", "absent"):
                    continue
            else:
                try:
                    crc = verify_file_checksum(path, artifact="tile")
                except FileNotFoundError:
                    continue
                ok_parse = True
                if crc != "mismatch":
                    try:
                        import numpy as np

                        np.load(path)
                    except Exception:
                        ok_parse = False
                if crc == "ok" and ok_parse:
                    continue
                if crc == "unstamped" and ok_parse:
                    if repair:
                        write_sidecar_for(path)
                    _issue(
                        issues, "tile", path, "unstamped",
                        _repair_action(repair, "restamped"),
                    )
                    continue
                status = "torn" if crc == "mismatch" else "corrupt"
            if _tile_in_use(store, level, tile_idx):
                need_rebuild = True
                _issue(issues, "tile", path, status, "pending_rebuild")
            else:
                if repair:
                    _remove_all(path, sidecar_path(path))
                _issue(
                    issues, "tile", path, "orphan",
                    _repair_action(repair, "removed"),
                )
    if need_rebuild:
        if repair and rebuild:
            try:
                rows = rebuild_pyramid(
                    folder, factor=geom_factor,
                    tile_len=geom_tile_len, codec=geom_codec,
                )
            except Exception as exc:
                log_event(
                    "integrity_pyramid_rebuild_failed",
                    folder=folder,
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                )
                _issue(
                    issues, "pyramid", tiles_dir, "corrupt", "failed",
                    f"rebuild raised {type(exc).__name__}: "
                    f"{str(exc)[:120]}",
                )
                return
            for it in issues:
                if it["action"] == "pending_rebuild":
                    it["action"] = "rebuilt_pyramid"
            _issue(
                issues, "pyramid", tiles_dir, "corrupt",
                "rebuilt_pyramid", f"{rows} level-0 rows resynced",
            )
        else:
            for it in issues:
                if it["action"] == "pending_rebuild":
                    it["action"] = "found"


# ---------------------------------------------------------------------------
# flight recorder segments (tpudas.obs.flight, ISSUE 13)


def _check_flight(folder: str, issues: list, repair: bool) -> None:
    """The flight ring's crash windows: a SIGKILL mid-flush tears the
    tail of the newest segment (per-line crc catches it); bit rot can
    corrupt any line.  Repair truncates each segment to its verified
    prefix — exactly what every reader already skips to — and removes
    a segment with no verified lines at all.  The trace is bounded,
    derived observability data: truncation loses nothing the readers
    could have used."""
    from tpudas.obs.flight import SEGMENT_RE, flight_dir, scan_segment
    from tpudas.utils.atomicio import atomic_write_text

    fdir = flight_dir(folder)
    if not os.path.isdir(fdir):
        return
    for name in sorted(os.listdir(fdir)):
        if not SEGMENT_RE.match(name):
            continue
        path = os.path.join(fdir, name)
        try:
            _records, good_lines, bad = scan_segment(path)
        except OSError as exc:
            if repair:
                _remove_all(path)
            _issue(
                issues, "flight", path, "corrupt",
                _repair_action(repair, "removed"),
                f"{type(exc).__name__}: {str(exc)[:120]}",
            )
            continue
        if not bad:
            continue
        if good_lines:
            if repair:
                atomic_write_text(path, "\n".join(good_lines) + "\n")
            _issue(
                issues, "flight", path, "torn",
                _repair_action(repair, "truncated"),
                f"{bad} unverifiable line(s) dropped",
            )
        else:
            if repair:
                _remove_all(path)
            _issue(
                issues, "flight", path, "torn",
                _repair_action(repair, "removed"),
                "no verifiable lines",
            )


# ---------------------------------------------------------------------------
# detect artifacts (tpudas.detect: carry + events ledger + score tiles)



def _detect_carry_status(path: str) -> tuple:
    """(status, parsed_or_None, detail) for one detect-carry rung."""
    from tpudas.detect.runner import _parse_detect_carry

    if not os.path.isfile(path):
        return "absent", None, ""
    try:
        crc = verify_file_checksum(path, artifact="detect_carry")
    except FileNotFoundError:
        return "absent", None, ""
    try:
        parsed = _parse_detect_carry(path)
    except Exception as exc:
        status = "torn" if crc == "mismatch" else "corrupt"
        return status, None, f"{type(exc).__name__}: {str(exc)[:120]}"
    if crc == "mismatch":
        return "torn", None, "crc32 mismatch"
    return ("unstamped" if crc == "unstamped" else "ok"), parsed, ""


def _ledger_file_status(path: str) -> tuple:
    """(status, events_or_None, detail) for one ledger rung: ok |
    unstamped | torn | corrupt | absent."""
    from tpudas.detect.ledger import ledger_status_text

    if not os.path.isfile(path):
        return "absent", None, ""
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as exc:
        return "corrupt", None, f"{type(exc).__name__}: {str(exc)[:120]}"
    status, events = ledger_status_text(text)
    return ("torn" if status == "torn" else status), events, (
        "bad line / crc mismatch / seq gap" if status == "torn" else ""
    )


def _reset_detect_state(folder, issues, repair, path, status, detail):
    """The detect repair of last resort: remove ``.detect/`` — the
    history recomputes deterministically from the output files."""
    if repair:
        from tpudas.detect.runner import reset_detect

        reset_detect(folder, f"audit: {detail or status}")
    _issue(
        issues, "detect", path, status,
        _repair_action(repair, "reset_detect"), detail,
    )


def _check_detect(folder: str, issues: list, repair: bool) -> None:
    from tpudas.detect.ledger import (
        DETECT_DIRNAME,
        LEDGER_FILENAME,
        ScoreStore,
        write_events,
    )
    from tpudas.detect.runner import DETECT_CARRY_FILENAME

    det = os.path.join(folder, DETECT_DIRNAME)
    if not os.path.isdir(det):
        return
    if not os.listdir(det):
        return  # an empty shell (partial creation) is not an issue
    # --- the carry (the subsystem's single commit point) -------------
    carry_path = os.path.join(det, DETECT_CARRY_FILENAME)
    status, parsed, detail = _detect_carry_status(carry_path)
    if status == "unstamped":
        if repair:
            write_sidecar_for(carry_path)
        _issue(
            issues, "detect_carry", carry_path, "unstamped",
            _repair_action(repair, "restamped"),
        )
        status = "ok"
    if status in ("torn", "corrupt", "absent"):
        p_status, p_parsed, p_detail = _detect_carry_status(
            carry_path + ".prev"
        )
        if p_status in ("ok", "unstamped"):
            if repair:
                _promote_prev(carry_path)
                if p_status == "unstamped":
                    write_sidecar_for(carry_path)
            parsed = p_parsed
            _issue(
                issues, "detect_carry", carry_path,
                "torn" if status == "absent" else status,
                _repair_action(repair, "promoted_prev"),
                detail or "orphaned .prev (primary missing)",
            )
        elif status == "absent" and p_status == "absent":
            # artifacts without any carry cannot be trusted (which
            # rows do they cover?)
            _reset_detect_state(
                folder, issues, repair, det, "corrupt",
                "detect artifacts without a carry",
            )
            return
        else:
            _reset_detect_state(
                folder, issues, repair, carry_path, status, detail
            )
            return
    committed_seq = int(parsed["meta"]["ledger_seq"])
    committed_rows = int(parsed["meta"]["score_rows"])
    # --- the events ledger -------------------------------------------
    ledger = os.path.join(det, LEDGER_FILENAME)
    l_status, events, l_detail = _ledger_file_status(ledger)
    if l_status in ("torn", "corrupt", "absent"):
        p_status, p_events, _pd = _ledger_file_status(ledger + ".prev")
        if p_status in ("ok", "unstamped"):
            if repair:
                _promote_prev(ledger)
            events = p_events
            _issue(
                issues, "events", ledger,
                "torn" if l_status == "absent" else l_status,
                _repair_action(repair, "promoted_prev"), l_detail,
            )
            l_status = p_status
        elif committed_seq == 0:
            # zero committed events is a HEALTHY state with no ledger
            # file at all (a commit that has never seen an event never
            # writes one) — absence is not a defect, and a bad rung is
            # repaired by truncating back to absence, never by
            # resetting the carry and score tiles
            if l_status != "absent":
                if repair:
                    _remove_all(ledger)
                _issue(
                    issues, "events", ledger, l_status,
                    _repair_action(repair, "removed"), l_detail,
                )
            events = []
            l_status = "ok"
        else:
            _reset_detect_state(
                folder, issues, repair, ledger, l_status or "corrupt",
                l_detail or "no loadable ledger rung",
            )
            return
    if l_status == "unstamped":
        if repair:
            write_events(folder, events)
        _issue(
            issues, "events", ledger, "unstamped",
            _repair_action(repair, "restamped"),
        )
    if len(events) < committed_seq:
        _reset_detect_state(
            folder, issues, repair, ledger, "corrupt",
            f"ledger holds {len(events)} events, carry committed "
            f"{committed_seq}",
        )
        return
    if len(events) > committed_seq:
        # a crashed commit's surplus — the runner's resume truncation,
        # made durable (the lines regenerate identically on replay)
        if repair:
            write_events(folder, events[:committed_seq])
        _issue(
            issues, "events", ledger, "torn",
            _repair_action(repair, "truncated"),
            f"{len(events) - committed_seq} uncommitted events",
        )
    # a bad .prev behind a healthy primary is dead weight: sweep it
    prev = ledger + ".prev"
    if os.path.isfile(prev):
        p_status, _pe, p_detail = _ledger_file_status(prev)
        if p_status in ("torn", "corrupt"):
            if repair:
                _remove_all(prev)
            _issue(
                issues, "events", prev, p_status,
                _repair_action(repair, "removed"), p_detail,
            )
    # --- the score tiles ---------------------------------------------
    scores_dir = ScoreStore.scores_dir(folder)
    if not os.path.isdir(scores_dir):
        if committed_rows > 0:
            _reset_detect_state(
                folder, issues, repair, scores_dir, "corrupt",
                f"carry committed {committed_rows} score rows but no "
                "score store exists",
            )
        return
    from tpudas.detect.ledger import (
        SCORES_MANIFEST,
        validate_scores_manifest,
    )

    manifest = os.path.join(scores_dir, SCORES_MANIFEST)
    _check_json_artifact(
        manifest, "scores_manifest", issues, repair,
        validate=validate_scores_manifest,
    )
    try:
        store = ScoreStore.open(folder)
    except Exception as exc:
        # e.g. CorruptDetectError: committed tail rows unrecoverable
        # (torn tails with no completed head tile) — the audit must
        # classify and reset, never crash the fsck
        _reset_detect_state(
            folder, issues, repair, scores_dir, "torn",
            f"{type(exc).__name__}: {str(exc)[:120]}",
        )
        return
    if store is None or store.n_rows < committed_rows:
        _reset_detect_state(
            folder, issues, repair, scores_dir, "corrupt",
            "score store cannot supply the carry's committed rows",
        )
        return
    # tiles + tails: restamp legacy, classify bad ones; an IN-USE bad
    # artifact is unreconcilable (scores are not rebuildable without
    # replaying rows) -> reset; an orphan beyond the manifest is swept
    n_full = len(store.tile_t0_rel)
    for name in sorted(os.listdir(scores_dir)):
        m = _TILE_NAME_RE.match(name)
        is_tails = name == "tails.npy"
        if m is None and not is_tails:
            continue
        path = os.path.join(scores_dir, name)
        try:
            crc = verify_file_checksum(path, artifact="scores_tile")
        except FileNotFoundError:
            continue
        ok_parse = True
        if crc != "mismatch":
            try:
                import numpy as np

                np.load(path)
            except Exception:
                ok_parse = False
        if crc == "ok" and ok_parse:
            continue
        if crc == "unstamped" and ok_parse:
            if repair:
                write_sidecar_for(path)
            _issue(
                issues, "scores", path, "unstamped",
                _repair_action(repair, "restamped"),
            )
            continue
        bad_status = "torn" if crc == "mismatch" else "corrupt"
        in_use = is_tails or int(m.group(1)) < n_full
        if is_tails and (committed_rows % store.tile_len) == 0:
            in_use = False  # no committed partial rows ride the tails
        if in_use:
            _reset_detect_state(
                folder, issues, repair, path, bad_status,
                "in-use score artifact failed verification",
            )
            return
        if repair:
            _remove_all(path, sidecar_path(path))
        _issue(
            issues, "scores", path, "orphan",
            _repair_action(repair, "removed"),
        )
    if store.n_rows > committed_rows:
        # a crashed commit's surplus rows: truncate back to the carry
        surplus = store.n_rows - committed_rows
        try:
            if repair:
                store.truncate_to(committed_rows)
            _issue(
                issues, "scores", scores_dir, "torn",
                _repair_action(repair, "truncated"),
                f"{surplus} uncommitted rows",
            )
        except Exception as exc:
            _reset_detect_state(
                folder, issues, repair, scores_dir, "corrupt",
                f"truncate failed: {type(exc).__name__}: "
                f"{str(exc)[:120]}",
            )


# ---------------------------------------------------------------------------

_REPAIRED_ACTIONS = (
    "removed",
    "promoted_prev",
    "restamped",
    "rewritten",
    "rebuilt_pyramid",
    "reset_detect",
    "truncated",
    "adopted_commit",
    "aborted",
)


def audit(folder, repair: bool = True, rebuild: bool = True) -> dict:
    """Scan (and with ``repair=True`` fix) every durable artifact in
    ``folder``.  Returns the report dict (see the module docstring);
    ``report["clean"]`` is True when nothing is left in a state a
    verified read would reject."""
    from tpudas.obs.health import HEALTH_FILENAME, validate_health
    from tpudas.io.index import INDEX_FILENAME
    from tpudas.resilience.quarantine import QUARANTINE_FILENAME

    folder = str(folder)
    t0 = time.perf_counter()
    issues: list = []
    with span("integrity.audit", folder=folder):
        if os.path.isdir(folder):
            _sweep_tmp(folder, issues, repair)
            _check_carry(folder, issues, repair)
            _check_json_artifact(
                os.path.join(folder, QUARANTINE_FILENAME), "quarantine",
                issues, repair,
            )
            _check_json_artifact(
                os.path.join(folder, HEALTH_FILENAME), "health", issues,
                repair, validate=validate_health,
            )
            _check_json_artifact(
                os.path.join(folder, INDEX_FILENAME), "index", issues,
                repair,
            )
            _check_outputs(folder, issues, repair)
            _check_pyramid(folder, issues, repair, rebuild)
            _check_detect(folder, issues, repair)
            _check_flight(folder, issues, repair)
    elapsed = time.perf_counter() - t0
    reg = get_registry()
    reg.counter(
        "tpudas_integrity_audit_runs_total",
        "integrity audits (startup fsck) executed",
    ).inc()
    reg.histogram(
        "tpudas_integrity_audit_seconds",
        "wall time of one integrity audit over an output folder",
    ).observe(elapsed)
    counts: dict = {}
    repaired = 0
    for it in issues:
        counts[it["status"]] = counts.get(it["status"], 0) + 1
        if it["action"] in _REPAIRED_ACTIONS:
            repaired += 1
            reg.counter(
                "tpudas_integrity_audit_repairs_total",
                "artifacts repaired by the integrity audit",
                labelnames=("kind",),
            ).inc(kind=it["action"])
    clean = all(it["action"] in _REPAIRED_ACTIONS for it in issues)
    report = {
        "folder": folder,
        "repair": bool(repair),
        "clean": bool(clean),
        "elapsed_s": round(elapsed, 4),
        "repaired": repaired,
        "counts": counts,
        "issues": issues,
    }
    if issues:
        log_event(
            "integrity_audit",
            folder=folder,
            clean=clean,
            repaired=repaired,
            counts=counts,
        )
    return report


def fleet_stream_dirs(root) -> list:
    """``[(stream_id, path), ...]`` for every stream root under a
    fleet root: the non-hidden subdirectories, sorted by name (the
    :class:`tpudas.fleet.FleetEngine` layout — stream ids cannot start
    with a dot, so dot-dirs beside the streams are fleet bookkeeping,
    e.g. a shared compile cache)."""
    root = str(root)
    out = []
    if os.path.isdir(root):
        for name in sorted(os.listdir(root)):
            if name.startswith("."):
                continue
            path = os.path.join(root, name)
            if os.path.isdir(path):
                out.append((name, path))
    return out


def audit_fleet(root, repair: bool = True, rebuild: bool = True) -> dict:
    """Run :func:`audit` over every stream root under ``root`` and
    aggregate: ``report["clean"]`` is True only when EVERY stream is.
    Per-stream reports land under ``report["streams"][stream_id]`` —
    each stream is classified and repaired independently, so a
    wrecked stream cannot block its neighbors' repair.  Run only
    while the fleet is stopped (the same tmp-sweep caveat as the
    single-stream audit)."""
    streams = {}
    issues_total = 0
    repaired_total = 0
    for stream_id, path in fleet_stream_dirs(root):
        rep = audit(path, repair=repair, rebuild=rebuild)
        streams[stream_id] = rep
        issues_total += len(rep["issues"])
        repaired_total += rep["repaired"]
    # a fleet root with nothing to audit is NOT clean: a typo'd path
    # or an emptied root must not read as a passing fsck
    error = None
    if not streams:
        error = (
            "no stream folders found under fleet root "
            f"{str(root)!r} (nothing was audited)"
        )
    report = {
        "root": str(root),
        "repair": bool(repair),
        "clean": bool(streams)
        and all(r["clean"] for r in streams.values()),
        "streams": streams,
        "stream_count": len(streams),
        "issues_total": issues_total,
        "repaired_total": repaired_total,
    }
    if error is not None:
        report["error"] = error
    if issues_total:
        log_event(
            "integrity_audit_fleet",
            root=str(root),
            clean=report["clean"],
            streams=len(streams),
            repaired=repaired_total,
        )
    return report


# ---------------------------------------------------------------------------
# backfill queue fsck (tpudas.backfill, ISSUE 12)

_STAGING_NAME_RE = re.compile(r"^(sh\d{5}|result)\.work\..+$")


def _backfill_shard_check(
    root, shard_id, queue, issues, repair, clock, rebuild=True
) -> None:
    """One shard's queue-side state: lease, done marker, committed
    directory, crash windows between them."""
    from tpudas.backfill.queue import DONE_DIRNAME, LEASES_DIRNAME

    lease_path = os.path.join(root, LEASES_DIRNAME, shard_id + ".json")
    done_path = os.path.join(root, DONE_DIRNAME, shard_id + ".json")
    sdir = queue.shard_dir(shard_id)
    done = queue.is_done(shard_id)
    # -- the done marker ------------------------------------------------
    if os.path.isfile(done_path) and not done:
        # torn marker: remove it — the committed directory (if any) is
        # re-adopted below, an absent one re-executes
        if repair:
            _remove_all(done_path)
        _issue(
            issues, "backfill_done", done_path, "torn",
            _repair_action(repair, "removed"), "crc32 mismatch",
        )
        done = False
    if done and not os.path.isdir(sdir):
        # a marker with no bytes behind it can only mislead the stitch
        if repair:
            _remove_all(done_path)
        _issue(
            issues, "backfill_done", done_path, "corrupt",
            _repair_action(repair, "removed"),
            "done marker without a committed shard directory",
        )
        done = False
    # -- the lease ------------------------------------------------------
    if os.path.isfile(lease_path):
        lease = queue.read_lease(shard_id)
        now_ns = int(float(clock()) * 1e9)
        if lease is None:
            if repair:
                _remove_all(lease_path)
            _issue(
                issues, "backfill_lease", lease_path, "torn",
                _repair_action(repair, "removed"), "unparseable lease",
            )
        elif done:
            if repair:
                _remove_all(lease_path)
            _issue(
                issues, "backfill_lease", lease_path, "stale_lease",
                _repair_action(repair, "removed"),
                "lease outlived its shard's commit",
            )
        elif int(lease.get("deadline_ns", 0)) < now_ns:
            if repair:
                _remove_all(lease_path)
            _issue(
                issues, "backfill_lease", lease_path, "stale_lease",
                _repair_action(repair, "removed"),
                f"deadline passed (worker {lease.get('worker')!r})",
            )
    # -- a committed directory without its marker -----------------------
    if os.path.isdir(sdir) and not done and not queue.is_parked(shard_id):
        # the crash window between the commit rename and the marker
        # write: verify the directory and adopt it (exactly what a
        # claiming worker would do)
        sub = audit(sdir, repair=repair, rebuild=rebuild)
        if sub["clean"]:
            if repair:
                from tpudas.backfill.queue import Lease

                queue._write_done(
                    shard_id,
                    Lease(shard=shard_id, token="fsck", worker="fsck"),
                    {"adopted": True},
                )
            _issue(
                issues, "backfill_commit", sdir, "torn",
                _repair_action(repair, "adopted_commit"),
                "committed directory without a done marker",
            )
        else:
            if repair:
                import shutil

                shutil.rmtree(sdir, ignore_errors=True)
            _issue(
                issues, "backfill_commit", sdir, "corrupt",
                _repair_action(repair, "removed"),
                "unverifiable committed directory (re-executes)",
            )


def audit_backfill(root, repair: bool = True, rebuild: bool = True,
                   clock=time.time) -> dict:
    """Fsck one backfill queue root (tpudas.backfill): verify the
    plan, sweep stale/torn leases and orphan staging directories,
    finish crashed commits (committed directory without its marker →
    verified + adopted; torn/bodiless done markers → removed so the
    shard re-executes), audit every committed shard and the stitched
    result with the standard per-folder :func:`audit`, and classify a
    half-stitched result.  Parked shards are REPORTED (counted, never
    "repaired" — re-running a parked shard is an operator decision).

    Run only while no worker is active on the root — live staging
    directories are distinguishable from orphans only by their lease,
    and the lease of a mid-drain worker may renew between our read
    and the sweep."""
    from tpudas.backfill.queue import (
        PARKED_DIRNAME,
        RESULT_DIRNAME,
        RESULT_DONE_FILENAME,
        SHARDS_DIRNAME,
        BackfillQueue,
    )

    root = str(root)
    t0 = time.perf_counter()
    issues: list = []
    shard_reports: dict = {}
    parked: list = []
    error = None
    with span("backfill.audit", root=root):
        try:
            queue = BackfillQueue(root, worker="fsck", clock=clock)
        except Exception as exc:
            queue = None
            error = (
                f"unreadable backfill plan: {type(exc).__name__}: "
                f"{str(exc)[:200]}"
            )
            log_event(
                "backfill_audit_plan_unreadable",
                root=root,
                error=error,
            )
            _issue(
                issues, "backfill_plan",
                os.path.join(root, "backfill.json"), "corrupt",
                "failed", error,
            )
        if queue is not None:
            from tpudas.backfill.queue import (
                DONE_DIRNAME,
                LEASES_DIRNAME,
            )

            # crashed bookkeeping writers leave tmp files beside the
            # leases/markers; sweep them (shard/result directories get
            # their own full audit below, tmp sweep included)
            for d in (LEASES_DIRNAME, DONE_DIRNAME, PARKED_DIRNAME):
                p = os.path.join(root, d)
                if os.path.isdir(p):
                    _sweep_tmp(p, issues, repair)
            shard_ids = [sh["id"] for sh in queue.plan["shards"]]
            live_tokens = set()
            for sid in shard_ids:
                lease = queue.read_lease(sid)
                now_ns = int(float(clock()) * 1e9)
                if (
                    lease is not None
                    and int(lease.get("deadline_ns", 0)) >= now_ns
                    and not queue.is_done(sid)
                ):
                    live_tokens.add(str(lease.get("token")))
                _backfill_shard_check(
                    root, sid, queue, issues, repair, clock,
                    rebuild=rebuild,
                )
                if queue.is_parked(sid):
                    parked.append(sid)
                if queue.is_done(sid):
                    shard_reports[sid] = audit(
                        queue.shard_dir(sid), repair=repair,
                        rebuild=rebuild,
                    )
            # orphan staging sweep: shard and result work dirs whose
            # token no live lease references (their writer is gone —
            # crashed, reclaimed, or lost the commit race)
            shards_dir = os.path.join(root, SHARDS_DIRNAME)
            candidates = []
            if os.path.isdir(shards_dir):
                candidates += [
                    os.path.join(shards_dir, n)
                    for n in sorted(os.listdir(shards_dir))
                ]
            candidates += [
                os.path.join(root, n) for n in sorted(os.listdir(root))
            ]
            for path in candidates:
                name = os.path.basename(path)
                m = _STAGING_NAME_RE.match(name)
                if m is None or not os.path.isdir(path):
                    continue
                token = name.split(".work.", 1)[1]
                if token in live_tokens:
                    continue
                if repair:
                    import shutil

                    shutil.rmtree(path, ignore_errors=True)
                _issue(
                    issues, "backfill_staging", path, "orphan",
                    _repair_action(repair, "removed"),
                    "staging directory with no live lease",
                )
            # the stitched result: half-committed states + a standard
            # per-folder audit of a committed one
            result_dir = os.path.join(root, RESULT_DIRNAME)
            done_path = os.path.join(root, RESULT_DONE_FILENAME)
            result_done = False
            if os.path.isfile(done_path):
                try:
                    _, status = read_json_verified(
                        done_path, "backfill_result"
                    )
                    result_done = status != "mismatch"
                except (OSError, ValueError):
                    result_done = False
                if not result_done:
                    if repair:
                        _remove_all(done_path)
                    _issue(
                        issues, "backfill_result", done_path, "torn",
                        _repair_action(repair, "removed"),
                        "unreadable result marker",
                    )
            if os.path.isdir(result_dir):
                if result_done:
                    shard_reports["result"] = audit(
                        result_dir, repair=repair, rebuild=rebuild,
                    )
                else:
                    # rename landed, marker missing: the stitch is a
                    # deterministic pure function of committed shards,
                    # so the cheap, always-correct repair is re-stitch
                    if repair:
                        import shutil

                        shutil.rmtree(result_dir, ignore_errors=True)
                    _issue(
                        issues, "backfill_result", result_dir, "torn",
                        _repair_action(repair, "removed"),
                        "half-committed result (re-stitch)",
                    )
            elif result_done:
                if repair:
                    _remove_all(done_path)
                _issue(
                    issues, "backfill_result", done_path, "corrupt",
                    _repair_action(repair, "removed"),
                    "result marker without a result directory",
                )
    elapsed = time.perf_counter() - t0
    get_registry().counter(
        "tpudas_integrity_audit_runs_total",
        "integrity audits (startup fsck) executed",
    ).inc()
    sub_clean = all(r["clean"] for r in shard_reports.values())
    repaired = sum(
        1 for it in issues if it["action"] in _REPAIRED_ACTIONS
    ) + sum(r["repaired"] for r in shard_reports.values())
    clean = (
        error is None
        and sub_clean
        and all(it["action"] in _REPAIRED_ACTIONS for it in issues)
    )
    report = {
        "root": root,
        "repair": bool(repair),
        "clean": bool(clean),
        "elapsed_s": round(elapsed, 4),
        "repaired": repaired,
        "parked": parked,
        "issues": issues,
        "shards": shard_reports,
        "issues_total": len(issues) + sum(
            len(r["issues"]) for r in shard_reports.values()
        ),
    }
    if error is not None:
        report["error"] = error
    if report["issues_total"]:
        log_event(
            "integrity_audit_backfill",
            root=root,
            clean=report["clean"],
            repaired=repaired,
            parked=len(parked),
        )
    return report


def _store_shard_check(queue, shard_id, issues, repair, clock) -> None:
    """One shard's object-store queue state: torn/bodiless done
    markers, torn/stale leases, crashed commits (verifying upload
    manifest without its marker → adopt), unverifiable manifests
    (→ removed, shard re-executes), orphan objects beyond the
    manifest.  Everything is read through ``list()`` + token
    verification — there is no directory to walk."""
    from tpudas.backfill.objqueue import SHARD_MANIFEST_NAME
    from tpudas.backfill.queue import Lease

    store = queue.store
    done_key = queue._done_key(shard_id)
    lease_key = queue._lease_key(shard_id)
    manifest_key = queue._manifest_key(shard_id)
    base = queue.shard_prefix(shard_id)
    # -- the done marker ------------------------------------------------
    done_payload, done_token = queue._get_verified(done_key)
    done = done_payload is not None
    if done_token is not None and not done:
        if repair:
            store.delete(done_key)
        _issue(
            issues, "backfill_done", done_key, "torn",
            _repair_action(repair, "removed"), "crc32 mismatch",
        )
    manifest = queue.shard_manifest(shard_id)
    verified = manifest is not None and queue.manifest_verifies(shard_id)
    if done and not verified:
        # a marker with no verifying bytes behind it can only mislead
        # the stitch — remove it, the shard re-executes
        if repair:
            store.delete(done_key)
        _issue(
            issues, "backfill_done", done_key, "corrupt",
            _repair_action(repair, "removed"),
            "done marker without a verifying upload manifest",
        )
        done = False
    # -- the lease ------------------------------------------------------
    lease_token = store.head(lease_key)
    if lease_token is not None:
        lease = queue.read_lease(shard_id)
        now_ns = int(float(clock()) * 1e9)
        if lease is None:
            if repair:
                store.delete(lease_key)
            _issue(
                issues, "backfill_lease", lease_key, "torn",
                _repair_action(repair, "removed"), "unparseable lease",
            )
        elif done:
            if repair:
                store.delete(lease_key)
            _issue(
                issues, "backfill_lease", lease_key, "stale_lease",
                _repair_action(repair, "removed"),
                "lease outlived its shard's commit",
            )
        elif int(lease.get("deadline_ns", 0)) < now_ns:
            if repair:
                store.delete(lease_key)
            _issue(
                issues, "backfill_lease", lease_key, "stale_lease",
                _repair_action(repair, "removed"),
                f"deadline passed (worker {lease.get('worker')!r})",
            )
    # -- a verifying manifest without its marker ------------------------
    if not done:
        if verified and not queue.is_parked(shard_id):
            # the crash window between the manifest upload and the
            # marker put: adopt (exactly what a claiming worker does)
            if repair:
                queue._write_done(
                    shard_id,
                    Lease(shard=shard_id, token="fsck", worker="fsck"),
                    {"adopted": True},
                )
                done = True
            _issue(
                issues, "backfill_commit", manifest_key, "torn",
                _repair_action(repair, "adopted_commit"),
                "verifying upload manifest without a done marker",
            )
        elif manifest is not None and not verified:
            # mid-upload crash (or torn/tampered object): the manifest
            # protects nothing — remove it so the shard re-executes
            # cleanly over the debris (uploads are idempotent)
            if repair:
                store.delete(manifest_key)
                manifest = None
            _issue(
                issues, "backfill_commit", manifest_key, "corrupt",
                _repair_action(repair, "removed"),
                "upload manifest fails token verification "
                "(re-executes)",
            )
        elif (
            manifest is None
            and store.head(manifest_key) is not None
        ):
            # present but unparseable — same verdict
            if repair:
                store.delete(manifest_key)
            _issue(
                issues, "backfill_commit", manifest_key, "torn",
                _repair_action(repair, "removed"),
                "unparseable upload manifest (re-executes)",
            )
    # -- orphan objects beyond the manifest -----------------------------
    listed = set((manifest or {}).get("objects", {}))
    for key in store.list(base):
        rel = key[len(base) + 1:]
        if rel == SHARD_MANIFEST_NAME or rel in listed:
            continue
        if repair:
            store.delete(key)
        _issue(
            issues, "store_object", key, "orphan",
            _repair_action(repair, "removed"),
            "object not named by the shard's upload manifest",
        )


def audit_backfill_store(store, prefix, repair: bool = True,
                         clock=time.time) -> dict:
    """Fsck one OBJECT-STORE backfill job prefix
    (:mod:`tpudas.backfill.objqueue`): verify the plan, sweep
    torn/stale leases, finish crashed commits (verifying upload
    manifest without its done marker → adopted; torn/bodiless markers
    → removed so the shard re-executes), classify orphan objects (not
    named by any upload manifest) and torn partial uploads
    (``store.list_uploads`` → aborted), and audit the stitched
    result's manifest the same way.  Everything is classified from
    ``list()`` + content-token verification — the store-plane
    equivalent of the directory walks in :func:`audit_backfill`.

    Committed shard BYTES are verified against their manifests'
    content tokens (that is what ``manifest_verifies`` does); the
    deep per-folder :func:`audit` runs on materialized local copies
    at stitch time instead.

    Run only while no worker is active on the prefix — same caveat
    as the POSIX fsck."""
    from tpudas.backfill.objqueue import (
        RESULT_DONE_KEY,
        RESULT_MANIFEST_KEY,
        RESULT_PREFIX,
        SHARDS_PREFIX,
        StoreBackfillQueue,
    )

    prefix = str(prefix).strip("/")
    root = f"store:{prefix}"
    t0 = time.perf_counter()
    issues: list = []
    parked: list = []
    error = None
    queue = None
    with span("backfill.audit", root=root):
        try:
            queue = StoreBackfillQueue(
                store, prefix, worker="fsck", clock=clock
            )
        except Exception as exc:
            error = (
                f"unreadable backfill plan: {type(exc).__name__}: "
                f"{str(exc)[:200]}"
            )
            log_event(
                "backfill_audit_plan_unreadable", root=root, error=error,
            )
            _issue(
                issues, "backfill_plan",
                f"{prefix}/backfill.json" if prefix else "backfill.json",
                "corrupt", "failed", error,
            )
        if queue is not None:
            shard_ids = [sh["id"] for sh in queue.plan["shards"]]
            for sid in shard_ids:
                _store_shard_check(queue, sid, issues, repair, clock)
                if queue.is_parked(sid):
                    parked.append(sid)
            # shard prefixes the plan does not know — debris from a
            # re-plan under a reused prefix, or key corruption
            known = set(shard_ids)
            shards_base = queue._key(SHARDS_PREFIX)
            for key in store.list(shards_base):
                sid = key[len(shards_base) + 1:].split("/", 1)[0]
                if sid in known:
                    continue
                if repair:
                    store.delete(key)
                _issue(
                    issues, "store_object", key, "orphan",
                    _repair_action(repair, "removed"),
                    f"object under unknown shard {sid!r}",
                )
            # torn partial uploads anywhere under the job prefix
            for key in store.list_uploads(prefix):
                if repair:
                    store.abort_upload(key)
                _issue(
                    issues, "store_upload", key, "torn",
                    _repair_action(repair, "aborted"),
                    "partial upload (crashed writer)",
                )
            # -- the stitched result -----------------------------------
            result_done_key = queue._key(RESULT_DONE_KEY)
            result_manifest_key = queue._key(RESULT_MANIFEST_KEY)
            result_base = queue._key(RESULT_PREFIX)
            done_payload, done_token = queue._get_verified(
                result_done_key
            )
            result_done = done_payload is not None
            if done_token is not None and not result_done:
                if repair:
                    store.delete(result_done_key)
                _issue(
                    issues, "backfill_result", result_done_key, "torn",
                    _repair_action(repair, "removed"),
                    "unreadable result marker",
                )
            rman, rman_token = queue._get_verified(result_manifest_key)
            rverified = rman is not None and all(
                store.head(f"{result_base}/{rel}") == tok
                for rel, tok in rman.get("objects", {}).items()
            )
            if result_done and not rverified:
                # marker without verifying bytes: the stitch is a
                # deterministic pure function of committed shards, so
                # the cheap, always-correct repair is re-stitch
                if repair:
                    store.delete(result_done_key)
                    store.delete(result_manifest_key)
                _issue(
                    issues, "backfill_result", result_done_key,
                    "corrupt", _repair_action(repair, "removed"),
                    "result marker without a verifying manifest "
                    "(re-stitch)",
                )
                result_done = False
            if not result_done and rman_token is not None:
                if repair:
                    store.delete(result_manifest_key)
                    rman = None
                _issue(
                    issues, "backfill_result", result_manifest_key,
                    "torn", _repair_action(repair, "removed"),
                    "half-committed result (re-stitch)",
                )
            listed = set((rman or {}).get("objects", {}))
            for key in store.list(result_base):
                rel = key[len(result_base) + 1:]
                if rel in listed:
                    continue
                if repair:
                    store.delete(key)
                _issue(
                    issues, "store_object", key, "orphan",
                    _repair_action(repair, "removed"),
                    "result object not named by the result manifest",
                )
    # replicated store: follow the structural audit with an
    # anti-entropy scrub so fsck leaves every mirror converged too
    # (the scrub drains the handoff journal first; repair follows the
    # fsck repair flag)
    replication = None
    from tpudas.store.replica import find_replicated

    repl = find_replicated(store)
    if repl is not None:
        try:
            replication = repl.scrub(prefix, repair=repair)
        except Exception as exc:
            log_event(
                "store_scrub_failed",
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            replication = {
                "clean": False,
                "error": f"{type(exc).__name__}: {str(exc)[:200]}",
            }
    elapsed = time.perf_counter() - t0
    get_registry().counter(
        "tpudas_integrity_audit_runs_total",
        "integrity audits (startup fsck) executed",
    ).inc()
    repaired = sum(
        1 for it in issues if it["action"] in _REPAIRED_ACTIONS
    )
    clean = error is None and all(
        it["action"] in _REPAIRED_ACTIONS for it in issues
    ) and (replication is None or bool(replication.get("clean")))
    report = {
        "root": root,
        "repair": bool(repair),
        "clean": bool(clean),
        "elapsed_s": round(elapsed, 4),
        "repaired": repaired,
        "parked": parked,
        "issues": issues,
        "counts": queue.counts() if queue is not None else {},
        "issues_total": len(issues),
    }
    if replication is not None:
        report["replication"] = replication
    if error is not None:
        report["error"] = error
    if report["issues_total"]:
        log_event(
            "integrity_audit_backfill",
            root=root,
            clean=report["clean"],
            repaired=repaired,
            parked=len(parked),
        )
    return report
