"""Window queries over the tile pyramid (with full-resolution fallback).

``QueryEngine.query(t0, t1, ...)`` answers a time x distance window
read in three steps:

1. **Level choice** — the coarsest pyramid level whose sample step
   still satisfies the requested ``resolution`` (seconds per sample) or
   ``max_samples`` budget; no constraint means full resolution.
2. **Tile assembly** — the window's tiles, through an LRU tile cache
   with **single-flight request coalescing**: concurrent identical tile
   loads share ONE disk read (the leader loads, followers wait on its
   event), so a thundering herd of dashboard clients costs one IO.
   Cache keys include the tile's valid-row count, so a growing tail
   tile is re-fetched after each pyramid append while full tiles stay
   cached forever (they are immutable).
3. **Full-resolution fallback** — windows (or window prefixes) older
   than the pyramid are served from the original output files via the
   :class:`tpudas.io.index.DirectoryIndex` time-range lookup, reduced
   on the fly to the chosen level's grid so a straddling window comes
   back on ONE uniform grid.

Results are honest about gaps: rows with no underlying data are NaN.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from tpudas.core.timeutils import to_datetime64
from tpudas.io.index import DirectoryIndex
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.serve.tiles import AGGS, TileStore, block_reduce
from tpudas.utils.logging import log_event

__all__ = ["QueryEngine", "QueryResult"]

_DEFAULT_CACHE_TILES = 256


@dataclass
class QueryResult:
    """One answered window query.

    ``times`` (datetime64[ns], leading-edge sample times), ``distance``
    (channel coordinates), ``data`` (times x distance, NaN where the
    stream has no data), plus the provenance the HTTP layer surfaces in
    response headers: pyramid ``level``, grid ``step_ns``, aggregate,
    and ``source`` ("tiles" | "files" | "mixed" | "empty").
    """

    times: np.ndarray
    distance: np.ndarray
    data: np.ndarray
    level: int
    step_ns: int
    agg: str
    source: str
    # True when the window was served ENTIRELY from completed
    # (immutable) full tiles: the response bytes can never change
    # short of a pyramid rebuild, so the HTTP layer may mark it
    # CDN-cacheable forever (SERVING.md "CDN deployment")
    immutable: bool = False

    @property
    def n_samples(self) -> int:
        return int(self.data.shape[0])


class _Flight:
    """One in-flight tile load (single-flight slot)."""

    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None


class QueryEngine:
    """Cached, coalesced window reads over one output folder."""

    def __init__(self, folder, cache_tiles: int = _DEFAULT_CACHE_TILES,
                 engine=None, tile_prefetch=None):
        self.folder = str(folder)
        self.engine = engine
        # optional hook ``(store, level, lo, hi)`` called before a
        # pyramid read: a RemotePyramid materializes the window's tile
        # objects into the local mirror so TileStore finds them
        # (tpudas.store.tileplane; None on a plain local folder)
        self.tile_prefetch = tile_prefetch
        self._store = TileStore.open(self.folder, engine=engine)
        self._index = DirectoryIndex(self.folder)
        self._cache: OrderedDict = OrderedDict()
        self._cache_cap = max(int(cache_tiles), 1)
        self._lock = threading.Lock()  # cache + in-flight table
        self._inflight: dict = {}
        # DirectoryIndex mutates its record dict in update(); two
        # concurrent fallback queries must not interleave an update
        # with a time_range_records iteration
        self._index_lock = threading.Lock()

    # -- store visibility ---------------------------------------------
    @property
    def store(self) -> TileStore | None:
        return self._store

    def has_pyramid(self) -> bool:
        """True when the folder has a (readable, non-empty) tile
        pyramid right now — cheap gate for callers that only want the
        engine when it can actually beat a full-resolution read
        (e.g. ``patch_waterfall``)."""
        store = self._refresh_store()
        return store is not None and store.head_ns is not None

    def _refresh_store(self) -> TileStore | None:
        """Pick up pyramid growth since the last query (the writer
        appends between polls; the manifest is the consistency
        point)."""
        if self._store is None:
            self._store = TileStore.open(self.folder, engine=self.engine)
        else:
            self._store.refresh()
        return self._store

    # -- the tile cache ------------------------------------------------
    def _tile_key(self, store, level, tile_idx):
        # keyed on (tile, valid rows, store generation, codec): valid
        # refreshes the growing head tile per append; generation+codec
        # key out a rebuild_pyramid re-encode — same tile index,
        # different bytes — so a re-encoded store can never serve a
        # stale pre-rebuild decoded array (ISSUE 11 cache fix)
        valid = min(
            store.tile_len, store.n(level) - tile_idx * store.tile_len
        )
        return (
            int(level), int(tile_idx), int(valid),
            int(store.generation), store.codec or "raw",
        )

    def cache_info(self) -> dict:
        with self._lock:
            return {
                "tiles": len(self._cache),
                "capacity": self._cache_cap,
            }

    def _cached_loader(self, store):
        """A ``loader(level, tile_idx)`` for :meth:`TileStore.read`
        that goes through the LRU cache with single-flight
        coalescing."""
        reg = get_registry()

        def load(level, tile_idx):
            key = self._tile_key(store, level, tile_idx)
            while True:
                with self._lock:
                    hit = self._cache.get(key)
                    if hit is not None:
                        self._cache.move_to_end(key)
                        reg.counter(
                            "tpudas_serve_cache_hits_total",
                            "tile reads answered from the LRU cache",
                        ).inc()
                        return hit
                    flight = self._inflight.get(key)
                    leader = flight is None
                    if leader:
                        flight = self._inflight[key] = _Flight()
                if not leader:
                    reg.counter(
                        "tpudas_serve_singleflight_coalesced_total",
                        "tile loads that waited on an identical "
                        "in-flight load instead of hitting disk",
                    ).inc()
                    flight.event.wait()
                    if flight.error is None:
                        return flight.value
                    # leader failed: surface the same failure here (a
                    # retry loop would hide real IO errors)
                    raise flight.error
                # from here on the leader MUST reach the finally that
                # sets flight.event / clears _inflight — even the
                # counter update stays inside, or a raise would wedge
                # every future request for this tile on event.wait()
                try:
                    reg.counter(
                        "tpudas_serve_cache_misses_total",
                        "tile reads that had to load from disk",
                    ).inc()
                    value = store._load_tile(level, tile_idx)
                except BaseException as exc:
                    flight.error = exc
                    raise
                else:
                    flight.value = value
                    with self._lock:
                        self._cache[key] = value
                        self._cache.move_to_end(key)
                        while len(self._cache) > self._cache_cap:
                            self._cache.popitem(last=False)
                            reg.counter(
                                "tpudas_serve_cache_evictions_total",
                                "tiles evicted from the LRU cache",
                            ).inc()
                        reg.gauge(
                            "tpudas_serve_cache_tiles",
                            "tiles currently held by the LRU cache",
                        ).set(len(self._cache))
                    return value
                finally:
                    flight.event.set()
                    with self._lock:
                        self._inflight.pop(key, None)

        return load

    # -- level selection ----------------------------------------------
    @staticmethod
    def pick_level(store: TileStore, t0_ns: int, t1_ns: int,
                   resolution=None, max_samples=None) -> int:
        """The coarsest level whose step still satisfies the requested
        resolution (seconds/sample) or sample budget; 0 when
        unconstrained."""
        res_sec = None
        if resolution is not None:
            res_sec = float(resolution)
        elif max_samples is not None and int(max_samples) > 0:
            res_sec = max((t1_ns - t0_ns) / 1e9 / int(max_samples), 0.0)
        if res_sec is None or res_sec <= 0:
            return 0
        level = 0
        for k in range(store.n_levels):
            if store.n(k) == 0 and k > 0:
                break
            if store.level_step_ns(k) / 1e9 <= res_sec:
                level = k
        return level

    # -- full-resolution fallback -------------------------------------
    def _file_rows(self, lo_ns: int, hi_ns: int, refresh: bool = True):
        """Full-resolution rows overlapping [lo_ns, hi_ns] read from
        the output files via the index's time-range lookup (no
        directory rescan beyond the incremental update; pass
        ``refresh=False`` when the caller already updated the index
        this request — one stat-scan per query, not per slab).
        Returns a list of contiguous (times_ns int64, data float
        (rows, C)) groups plus the distance coords (None when no
        data)."""
        from tpudas.io.registry import read_file
        from tpudas.io.spool import merge_patches

        lo = np.datetime64(int(lo_ns), "ns")
        hi = np.datetime64(int(hi_ns), "ns")
        with self._index_lock:
            if refresh:
                self._index.update()
            recs = self._index.time_range_records(lo, hi)
        patches = []
        for rec in recs:
            patches.extend(
                read_file(
                    rec["path"],
                    format=rec.get("format", "dasdae"),
                    time=(lo, hi),
                )
            )
        get_registry().counter(
            "tpudas_serve_fallback_reads_total",
            "full-resolution output files read for windows older "
            "than (or without) the pyramid",
        ).inc(float(len(recs)))
        groups = []
        distance = None
        for p in merge_patches(patches):
            data = p.host_data()
            ax = p.axis_of("time")
            if ax != 0:
                data = np.moveaxis(data, ax, 0)
            times = (
                np.asarray(p.coords["time"])
                .astype("datetime64[ns]")
                .astype(np.int64)
            )
            if times.size:
                groups.append((times, np.asarray(data, dtype=np.float64)))
                if distance is None:
                    distance = np.asarray(
                        p.coords.get("distance", ()), dtype=np.float64
                    )
        return groups, distance

    def _file_coverage_ns(self):
        """(earliest time_min, latest time_max) over the folder's
        indexed files as epoch ns, or (None, None) when empty — the
        bound that keeps file-fallback grids sized by DATA, not by
        whatever window a client asked for."""
        with self._index_lock:
            self._index.update()
            recs = self._index.time_range_records(None, None)
        if not recs:
            return None, None
        lo = min(
            np.datetime64(r["time_min"], "ns").astype(np.int64)
            for r in recs
        )
        hi = max(
            np.datetime64(r["time_max"], "ns").astype(np.int64)
            for r in recs
        )
        return int(lo), int(hi)

    # level-0 rows materialized per slab of the file-fallback grid
    # (~8 MB/channel-hundred of float64): bounds peak memory however
    # large the (data-clamped) span is
    _FILE_GRID_SLAB = 1_048_576

    def _files_on_level_grid(self, store, level, i_lo, i_hi, agg):
        """The [i_lo, i_hi) span of the level grid assembled from
        full-resolution files (pre-pyramid ``i < 0``, or beyond-head
        ``i >= n``).  Missing rows are NaN; coarse rows are reduced on
        the fly with the same kernel the pyramid cascade uses.
        Assembled in bounded slabs — the caller clamps the span to
        actual file coverage, this bounds the per-slab allocation."""
        f = int(store.factor) ** int(level)
        step0 = int(store.step_ns)
        group_slab = max(self._FILE_GRID_SLAB // f, 1)
        parts = []
        for g_lo in range(int(i_lo), int(i_hi), group_slab):
            g_hi = min(g_lo + group_slab, int(i_hi))
            lo0, hi0 = g_lo * f, g_hi * f
            lo_ns = store.t0_ns + lo0 * step0
            hi_ns = store.t0_ns + (hi0 - 1) * step0
            # the caller's _file_coverage_ns already refreshed the
            # index this request
            groups, _ = self._file_rows(lo_ns, hi_ns, refresh=False)
            grid = np.full(
                (hi0 - lo0, int(store.n_ch)), np.nan, np.float64
            )
            for t_ns, data in groups:
                idx = np.round(
                    (t_ns - int(store.t0_ns)) / step0
                ).astype(np.int64)
                ok = (
                    (np.abs(t_ns - (store.t0_ns + idx * step0))
                     <= 0.01 * step0)
                    & (idx >= lo0)
                    & (idx < hi0)
                )
                if data.shape[1] == grid.shape[1]:
                    grid[idx[ok] - lo0] = data[ok]
                else:
                    # mismatched channel geometry: the rows stay NaN,
                    # but never silently — the append side raises
                    # loudly for the same condition
                    log_event(
                        "serve_fallback_channel_mismatch",
                        file_channels=int(data.shape[1]),
                        pyramid_channels=int(grid.shape[1]),
                    )
            if level == 0:
                parts.append(grid.astype(np.float32))
            else:
                parts.append(
                    block_reduce(grid, f, agg, self.engine).astype(
                        np.float32
                    )
                )
        if not parts:
            return np.empty((0, int(store.n_ch)), np.float32)
        return np.concatenate(parts, axis=0)

    # -- the query -----------------------------------------------------
    def query(
        self,
        t0,
        t1,
        distance=None,
        resolution=None,
        max_samples=None,
        agg: str = "mean",
    ) -> QueryResult:
        """Answer one [t0, t1] x distance window read.

        ``resolution`` (seconds/sample) or ``max_samples`` picks the
        coarsest satisfying pyramid level; ``distance`` is an optional
        ``(lo, hi)`` channel-coordinate range; ``agg`` is ``"mean"``
        (default), ``"min"`` or ``"max"`` (levels above 0 carry all
        three).  Windows (or prefixes) older than the pyramid fall back
        to the full-resolution output files.
        """
        if agg not in AGGS:
            raise ValueError(f"unknown aggregate {agg!r}; known: {AGGS}")
        t0_ns = int(to_datetime64(t0).astype("datetime64[ns]").astype(np.int64))
        t1_ns = int(to_datetime64(t1).astype("datetime64[ns]").astype(np.int64))
        if t1_ns < t0_ns:
            raise ValueError(f"empty/inverted window: t1 {t1} < t0 {t0}")
        store = self._refresh_store()
        reg = get_registry()
        with span("serve.query", agg=agg):
            if store is None or store.head_ns is None:
                result = self._query_files_only(
                    t0_ns, t1_ns, agg, resolution, max_samples
                )
            else:
                result = self._query_pyramid(
                    store, t0_ns, t1_ns, resolution, max_samples, agg
                )
        result = self._select_distance(result, distance)
        reg.counter(
            "tpudas_serve_queries_total",
            "window queries answered, by data source",
            labelnames=("source",),
        ).inc(source=result.source)
        return result

    def _query_pyramid(self, store, t0_ns, t1_ns, resolution, max_samples,
                       agg) -> QueryResult:
        level = self.pick_level(store, t0_ns, t1_ns, resolution, max_samples)
        stepk = store.level_step_ns(level)
        rel0 = t0_ns - store.t0_ns
        rel1 = t1_ns - store.t0_ns
        i_lo = -(-rel0 // stepk)  # ceil: first sample time >= t0
        i_hi = rel1 // stepk + 1  # past the last sample time <= t1
        n_k = store.n(level)
        if i_lo < 0 or i_hi > n_k:
            # the span beyond the pyramid comes from files: clamp it
            # to actual file coverage FIRST, so the grid is sized by
            # data on disk, never by the window a client asked for
            # (t0=1970 must not allocate fifty years of NaN)
            cov_lo, cov_hi = self._file_coverage_ns()
            if i_lo < 0:
                i_lo = (
                    max(i_lo, (cov_lo - store.t0_ns) // stepk)
                    if cov_lo is not None
                    else 0
                )
            if i_hi > n_k:
                i_hi = (
                    max(
                        min(i_hi, (cov_hi - store.t0_ns) // stepk + 1),
                        n_k,
                    )
                    if cov_hi is not None
                    else n_k
                )
        if i_hi <= i_lo:
            return self._empty(store, level, stepk, agg)
        parts = []
        source = []
        # pre-pyramid prefix (i < 0) from full-resolution files
        i_mid = min(max(i_lo, 0), i_hi)
        if i_lo < i_mid:
            parts.append(
                self._files_on_level_grid(store, level, i_lo, i_mid, agg)
            )
            source.append("files")
        # the pyramid-covered span
        i_tiles_hi = min(i_hi, max(n_k, i_mid))
        if i_mid < i_tiles_hi:
            if self.tile_prefetch is not None:
                self.tile_prefetch(store, level, i_mid, i_tiles_hi)
            parts.append(
                store.read(
                    level, i_mid, i_tiles_hi, agg=agg,
                    loader=self._cached_loader(store),
                )
            )
            source.append("tiles")
        i_hi_eff = i_tiles_hi
        # beyond-the-head suffix: output files the pyramid has not
        # absorbed yet (a lagging or failing append must DEGRADE the
        # read path to the files, not hide new data); trailing rows
        # with no file backing are trimmed, so a window past all data
        # still comes back empty rather than NaN-padded
        i_post = max(i_lo, n_k)
        if i_hi > i_post:
            suffix = self._files_on_level_grid(
                store, level, i_post, i_hi, agg
            )
            backed = np.isfinite(suffix).any(axis=1)
            n_keep = (
                int(np.max(np.nonzero(backed)[0])) + 1
                if backed.any()
                else 0
            )
            if n_keep:
                parts.append(suffix[:n_keep])
                source.append("files")
                i_hi_eff = i_post + n_keep
        if not parts:
            return self._empty(store, level, stepk, agg)
        data = np.concatenate(parts, axis=0)
        times = (
            np.asarray(store.t0_ns + np.arange(i_lo, i_hi_eff) * stepk)
            .astype("datetime64[ns]")
        )
        # immutable = every row came from a COMPLETED full tile (no
        # file fallback, no growing head tile): those bytes are
        # append-proof, so the HTTP layer can mark the response
        # CDN-cacheable forever
        n_full_rows = (n_k // store.tile_len) * store.tile_len
        return QueryResult(
            times=times,
            distance=np.asarray(store.distance, dtype=np.float64),
            data=data,
            level=int(level),
            step_ns=int(stepk),
            agg=agg,
            source=(
                "mixed" if len(set(source)) > 1 else source[0]
            ),
            immutable=bool(
                set(source) == {"tiles"} and i_hi_eff <= n_full_rows
            ),
        )

    def _query_files_only(self, t0_ns, t1_ns, agg, resolution=None,
                          max_samples=None) -> QueryResult:
        """No pyramid at all (legacy folder): serve the files' rows,
        still honoring the caller's resolution/sample budget by
        reducing on the fly — a ``/waterfall?max_px=1024`` over a
        month of legacy output must not ship the month at full
        resolution.  The window is clamped to file coverage before
        anything is read."""
        cov_lo, cov_hi = self._file_coverage_ns()
        if cov_lo is not None:
            t0_ns = max(int(t0_ns), cov_lo)
            t1_ns = min(int(t1_ns), cov_hi)
        if cov_lo is None or t1_ns < t0_ns:
            return QueryResult(
                times=np.empty(0, dtype="datetime64[ns]"),
                distance=np.empty(0),
                data=np.empty((0, 0), np.float32),
                level=0, step_ns=0, agg=agg, source="empty",
            )
        groups, distance = self._file_rows(t0_ns, t1_ns, refresh=False)
        groups = [
            (t[(t >= t0_ns) & (t <= t1_ns)],
             d[(t >= t0_ns) & (t <= t1_ns)])
            for t, d in groups
        ]
        groups = [(t, d) for t, d in groups if t.size]
        if not groups:
            return QueryResult(
                times=np.empty(0, dtype="datetime64[ns]"),
                distance=(
                    np.empty(0)
                    if distance is None
                    else np.asarray(distance, np.float64)
                ),
                data=np.empty((0, 0 if distance is None else len(distance)),
                              np.float32),
                level=0, step_ns=0, agg=agg, source="empty",
            )
        times = np.concatenate([t for t, _ in groups]).astype(
            "datetime64[ns]"
        )
        data = np.concatenate([d for _, d in groups], axis=0).astype(
            np.float32
        )
        step_ns = (
            int(np.median(np.diff(times.astype(np.int64))))
            if times.size > 1
            else 0
        )
        # on-the-fly budget reduction (the no-pyramid analogue of the
        # pyramid's level choice): group-mean/min/max on the native
        # grid, gaps NaN-filled so reduction stays honest
        res_sec = None
        if resolution is not None:
            res_sec = float(resolution)
        elif max_samples is not None and int(max_samples) > 0:
            res_sec = (t1_ns - t0_ns) / 1e9 / int(max_samples)
        if res_sec is not None and step_ns > 0:
            m = int(res_sec * 1e9 // step_ns)
            if m >= 2 and times.size:
                t_ns = times.astype(np.int64)
                first = int(t_ns[0])
                idx = np.round((t_ns - first) / step_ns).astype(np.int64)
                n_grid = int(idx[-1]) + 1
                g = n_grid // m
                if g >= 1:
                    grid = np.full(
                        (g * m, data.shape[1]), np.nan, np.float64
                    )
                    ok = idx < g * m
                    grid[idx[ok]] = data[ok]
                    data = block_reduce(grid, m, agg, self.engine).astype(
                        np.float32
                    )
                    times = (
                        first
                        + np.arange(g, dtype=np.int64) * (m * step_ns)
                    ).astype("datetime64[ns]")
                    step_ns = m * step_ns
        return QueryResult(
            times=times,
            distance=np.asarray(distance, np.float64),
            data=data,
            level=0, step_ns=step_ns, agg=agg, source="files",
        )

    def _empty(self, store, level, stepk, agg) -> QueryResult:
        return QueryResult(
            times=np.empty(0, dtype="datetime64[ns]"),
            distance=np.asarray(store.distance, dtype=np.float64),
            data=np.empty((0, int(store.n_ch)), np.float32),
            level=int(level), step_ns=int(stepk), agg=agg, source="empty",
        )

    @staticmethod
    def _select_distance(result: QueryResult, distance) -> QueryResult:
        if distance is None or result.distance.size == 0:
            return result
        lo, hi = distance
        d = result.distance
        mask = np.ones(d.shape[0], dtype=bool)
        if lo is not None:
            mask &= d >= float(lo)
        if hi is not None:
            mask &= d <= float(hi)
        result.distance = d[mask]
        result.data = result.data[:, mask]
        return result

    # -- maintenance ----------------------------------------------------
    def clear_cache(self) -> None:
        with self._lock:
            self._cache.clear()
        get_registry().gauge(
            "tpudas_serve_cache_tiles",
            "tiles currently held by the LRU cache",
        ).set(0)
        log_event("serve_cache_cleared", folder=os.path.basename(self.folder))
