"""Zero-dependency HTTP server over processed-output folders.

``ThreadingHTTPServer`` (stdlib, thread per connection) fronted by a
**bounded admission gate**: at most ``max_inflight`` data-plane
requests execute at once, and a request that arrives with the gate
full is shed IMMEDIATELY with ``503 + Retry-After`` instead of
queueing behind a backlog it can only deepen (graceful degradation,
the tpudas.resilience posture).  Control-plane endpoints
(``/healthz``, ``/metrics``, ``/fleet/healthz``) bypass the gate — an
operator must be able to see a saturated server's health *because* it
is saturated.

One server fronts one folder, a whole fleet, or both (ISSUE 8): every
data/health endpoint below is additionally mounted per stream at
``/s/<stream_id>/...`` (``DASServer(streams={...})`` or
``DASServer.for_fleet(root)``, which mounts every non-hidden
``root/<stream_id>/`` directory), all streams share the ONE admission
gate and process registry (``/metrics`` is the merged exposition by
construction), and ``/fleet/healthz`` aggregates every stream's
``health.json`` into one operator view.

Endpoints (all GET):

- ``/query``     — windowed array read (``t0``/``t1`` ISO-8601 or ns
  ints, optional ``d0``/``d1`` distance bounds, ``resolution`` s/sample
  or ``max_samples``, ``agg`` mean|min|max, ``format`` npy|json).
- ``/waterfall`` — downsampled raster tile: same window params plus
  ``max_px`` (time-axis pixel budget, default 1024); picks the pyramid
  level from the budget and adds symmetric 95th-percentile color
  limits in ``X-Tpudas-Clim-*`` headers.
- ``/tile``      — one pyramid tile by address (``level``, ``idx``):
  the CDN-shaped read path (ISSUE 11).  Completed tiles are immutable
  and ship with a strong ETag + ``Cache-Control: public,
  max-age=31536000, immutable``; the partial head tile is
  ``no-cache``.  On a compressed store, ``Accept-Encoding: x-tpt``
  gets the stored :mod:`tpudas.codec` blob verbatim.
- ``/live``      — Server-Sent-Events push of the decimated stream
  (ISSUE 19, :mod:`tpudas.live`): ``hello``, a pyramid-backed
  ``snapshot`` through the same query path as ``/query``, then one
  codec-compressed ``delta`` per round; ``Last-Event-ID`` resumes.
  Requires a live producer (``TPUDAS_LIVE=1`` in-process, or a
  ``--live-bridge`` feed) — otherwise 503 + ``Retry-After``.

Every data-plane response carries a strong content-derived ``ETag``
and honors ``If-None-Match`` (``304`` with no body on a match), and
``/query``/``/waterfall`` bodies are ``deflate``-encoded when the
client asks (``Accept-Encoding: deflate``) — so a CDN/edge cache
absorbs the immutable traffic and revalidates the rest for header
cost.  See SERVING.md "CDN deployment".
- ``/events``    — the detection query plane (tpudas.detect): events
  from the integrity-verified ledger filtered by time window
  (``t0``/``t1``, optional), channel range (``c0``/``c1``),
  ``min_score``, ``op``, ``kind``, capped at ``limit`` (default
  1000); ``scores=1`` additionally returns the per-channel score rows
  in the window from the score tile store.
- ``/healthz``   — the stream's last good ``health.json`` snapshot
  (``tpudas.obs.health.read_health`` — the file stays the crash-safe
  source of truth; this is its live read path).
- ``/metrics``   — the LIVE process registry in Prometheus text
  exposition (the ``metrics.prom`` file snapshot remains for the
  node-exporter textfile collector).  Process-wide: in a fleet this
  is already the merged view over every stream.
- ``/s/<stream_id>/query`` (``/waterfall`` ``/events`` ``/healthz``)
  — the same endpoints scoped to one mounted stream.
- ``/fleet/healthz`` — aggregate health over every mounted stream:
  per-stream status (``ok`` / ``degraded`` / ``unknown``), counts,
  per-stream ``realtime_factor`` / ``head_lag_seconds``, the
  freshness-SLO evaluation, the fleet park/unpark event (timestamps
  included), and an overall status that is ``ok`` only when every
  stream is.
- ``/trace``      — recent spans and flight records (ISSUE 13):
  ``kind`` (default ``span``), ``name``, ``limit``.  A mounted
  folder with a flight ring serves its crash-surviving on-disk
  records; otherwise the in-memory span ring answers.  Control
  plane (bypasses the admission gate).
- ``/slo``        — per-stream freshness SLO status
  (``tpudas.obs.collect``): current head-lag vs ``target`` plus the
  error-budget burn over recent flight rounds (``objective``,
  ``window``).  Control plane.

``npy`` responses carry provenance headers (``X-Tpudas-Level``,
``X-Tpudas-Step-Ns``, ``X-Tpudas-Source``, ``X-Tpudas-T0-Ns``, ...);
``json`` responses embed the same fields (NaN rows serialize as
``null``).  See SERVING.md for the endpoint reference and runbook.

Operator entry point::

    python -m tpudas.serve.http <output_folder> --port 8000
"""

from __future__ import annotations

import io
import json
import os
import socket
import threading
import time
import urllib.parse
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from tpudas.codec import TILE_BLOB_SUFFIX, decode_tile, read_tile_header
from tpudas.core.timeutils import to_datetime64
from tpudas.integrity.checksum import crc32_hex
from tpudas.obs.health import read_health
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.resilience.faults import TransientFaultError, fault_point
from tpudas.serve.query import QueryEngine
from tpudas.utils.logging import log_event

__all__ = ["DASServer", "start_server", "serve_forever"]

_DEFAULT_MAX_INFLIGHT = 8
_DATA_ENDPOINTS = ("/query", "/waterfall", "/events", "/tile")
_DEFAULT_EVENTS_LIMIT = 1000
_DEFAULT_SCORES_LIMIT = 10000
# completed full tiles (and windows served entirely from them) can
# never change short of a pyramid rebuild: let a CDN keep them forever
_IMMUTABLE_CC = "public, max-age=31536000, immutable"
# everything touching mutable state (tails, head tile, file fallback)
# must revalidate at origin every time — the ETag makes that a 304
_MUTABLE_CC = "no-cache"
# the custom Accept-Encoding token under which /tile ships the stored
# compressed blob verbatim (self-describing tpudas.codec container)
_TPT_CODING = "x-tpt"
_MIN_DEFLATE_BYTES = 256


class _Mount:
    """One mounted output folder: its query engine plus the per-mount
    ledger/score-store caches.  The root mount serves the bare
    endpoints; stream mounts serve ``/s/<stream_id>/...``.

    ``remote`` (a :class:`tpudas.store.tileplane.RemotePyramid`) makes
    this a STATELESS SERVING REPLICA: ``folder`` is the remote's local
    mirror directory, tile objects materialize lazily per query
    through the NVMe read-through cache, and the mount's whole durable
    state can be wiped and re-hydrated from the object store."""

    def __init__(self, folder, stream_id=None, cache_tiles=256,
                 engine=None, remote=None):
        self.folder = str(folder)
        self.stream_id = stream_id
        self.remote = remote
        self.engine = QueryEngine(
            self.folder, cache_tiles=cache_tiles, engine=engine,
            tile_prefetch=None if remote is None else remote.prefetch,
        )
        self._events_cache = None
        self._score_store_cache = None
        self._slo_cache = None
        self._devprof_cache = None


def _slo_status_cached(mount, policy, health=None):
    """``slo_status`` cached on the mount, keyed by the policy plus
    the newest flight segment's ``(mtime_ns, size)`` — the expensive
    part is scanning + crc-verifying the ring, and the ring only
    changes when a round flushes.  A monitor polling
    ``/fleet/healthz`` every few seconds must not re-verify megabytes
    of JSONL per stream per request (the tile/ledger caches'
    stat-gated discipline)."""
    from tpudas.obs.collect import slo_status
    from tpudas.obs.flight import segment_paths
    from tpudas.obs.health import HEALTH_FILENAME

    def _stat_key(path):
        try:
            st = os.stat(path)
            return (path, st.st_mtime_ns, st.st_size)
        except OSError:
            return (path, None)

    segs = segment_paths(mount.folder)
    key = None
    if segs:
        # keyed on BOTH the newest flight segment and health.json: a
        # stream running with TPUDAS_FLIGHT=0 over an old ring still
        # updates health every round, and the current-lag half of the
        # SLO must track it
        key = (
            policy,
            _stat_key(segs[-1]),
            _stat_key(os.path.join(mount.folder, HEALTH_FILENAME)),
        )
        cached = mount._slo_cache
        if cached is not None and cached[0] == key:
            return cached[1]
    result = slo_status(mount.folder, policy, health=health)
    if key is not None:
        mount._slo_cache = (key, result)
    return result


def _devprof_entry_cached(mount):
    """The flight ring's devprof fold (ISSUE 17:
    :func:`tpudas.obs.collect.devprof_entry`), cached on the mount
    keyed by the newest flight segment's ``(mtime_ns, size)`` — the
    same stat-gated discipline as the SLO cache, for the same reason:
    ``/fleet/healthz`` polls must not rescan the ring per request."""
    from tpudas.obs.collect import devprof_entry
    from tpudas.obs.flight import read_flight, segment_paths

    segs = segment_paths(mount.folder)
    if not segs:
        return None
    try:
        st = os.stat(segs[-1])
        key = (segs[-1], st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    if key is not None:
        cached = mount._devprof_cache
        if cached is not None and cached[0] == key:
            return cached[1]
    result = devprof_entry(read_flight(mount.folder, kind="round",
                                       limit=200))
    if key is not None:
        mount._devprof_cache = (key, result)
    return result


def _load_events_cached(mount):
    """The parsed + crc-verified ledger, cached on the mount keyed by
    the primary file's ``(mtime_ns, size)`` — a dashboard polling
    ``/events`` every second must not re-read and re-verify the whole
    history per request (the tile cache's discipline; here a stat
    suffices because every commit atomically replaces the file).
    Absent-primary (``.prev``-fallback) reads are not cached."""
    from tpudas.detect.ledger import ledger_path, load_events

    try:
        st = os.stat(ledger_path(mount.folder))
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    if key is not None:
        cached = mount._events_cache
        if cached is not None and cached[0] == key:
            return cached[1]
    events = load_events(mount.folder)
    if key is not None:
        mount._events_cache = (key, events)
    return events


def _open_score_store_cached(mount):
    """``ScoreStore.open`` cached on the mount keyed by the scores
    manifest's ``(mtime_ns, size)`` — every commit (and truncation)
    atomically rewrites the manifest, so a stat decides freshness the
    same way :func:`_load_events_cached` does for the ledger.  Raises
    propagate uncached (the caller owns the degrade path)."""
    from tpudas.detect.ledger import SCORES_MANIFEST, ScoreStore

    manifest = os.path.join(
        ScoreStore.scores_dir(mount.folder), SCORES_MANIFEST
    )
    try:
        st = os.stat(manifest)
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    if key is not None:
        cached = mount._score_store_cache
        if cached is not None and cached[0] == key:
            return cached[1]
    store = ScoreStore.open(mount.folder)
    if key is not None:
        mount._score_store_cache = (key, store)
    return store


class _AdmissionGate:
    """Bounded concurrent-request gate with immediate shedding."""

    def __init__(self, limit: int):
        self.limit = max(int(limit), 1)
        self._sem = threading.BoundedSemaphore(self.limit)
        self._lock = threading.Lock()
        self.in_use = 0

    def try_enter(self) -> bool:
        try:
            # deterministic saturation for tests: an injected fault at
            # this site reads as "gate full"
            fault_point("serve.queue_full")
        except TransientFaultError:
            return False
        if not self._sem.acquire(blocking=False):
            return False
        with self._lock:
            self.in_use += 1
            depth = self.in_use
        get_registry().gauge(
            "tpudas_serve_inflight",
            "data-plane requests currently executing",
        ).set(depth)
        return True

    def leave(self) -> None:
        with self._lock:
            self.in_use -= 1
            depth = self.in_use
        self._sem.release()
        get_registry().gauge(
            "tpudas_serve_inflight",
            "data-plane requests currently executing",
        ).set(depth)


def _parse_time(raw: str):
    """ISO-8601 string or integer nanoseconds."""
    s = str(raw).strip()
    if s.lstrip("-").isdigit():
        return np.datetime64(int(s), "ns")
    return to_datetime64(s)


def _params(query: str) -> dict:
    return {
        k: v[-1] for k, v in urllib.parse.parse_qs(query).items()
    }


def _json_safe(data: np.ndarray):
    """Nested lists with NaN -> None (JSON has no NaN)."""
    out = []
    for row in np.asarray(data, dtype=np.float64):
        out.append(
            [None if not np.isfinite(v) else float(v) for v in row]
        )
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpudas-serve/1"
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------
    def log_message(self, fmt, *args):  # stdlib stderr chatter -> JSONL
        log_event("serve_access", line=(fmt % args)[:200])

    def _send(self, status: int, body: bytes, content_type: str,
              headers=()):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: dict, headers=()):
        body = (json.dumps(payload, indent=1) + "\n").encode()
        self._send(status, body, "application/json", headers)

    # -- HTTP caching helpers (ISSUE 11) -------------------------------
    def _accepts(self, coding: str) -> bool:
        """Whether the request accepts one content-coding token —
        q-values honored, so ``deflate;q=0`` is an explicit refusal,
        not a match."""
        for item in self.headers.get("Accept-Encoding", "").split(","):
            token, _, params = item.partition(";")
            if token.strip().lower() != coding:
                continue
            q = 1.0
            for p in params.split(";"):
                k, _, v = p.partition("=")
                if k.strip().lower() == "q":
                    try:
                        q = float(v.strip())
                    except ValueError:
                        q = 0.0
            return q > 0.0
        return False

    def _maybe_deflate(self, body: bytes) -> tuple:
        """(body, extra_headers): deflate-encode a data-plane body the
        client asked for (``Accept-Encoding: deflate``) when it is
        big enough to be worth it.  ``Vary`` is always set — the
        representation depends on the request's encoding, cached
        intermediaries must key on it."""
        headers = [("Vary", "Accept-Encoding")]
        if self._accepts("deflate") and len(body) > _MIN_DEFLATE_BYTES:
            body = zlib.compress(body, 6)
            headers.append(("Content-Encoding", "deflate"))
        return body, headers

    def _send_cacheable(self, body: bytes, content_type: str,
                        headers, cache_control: str) -> int:
        """Send one data-plane representation with a strong
        content-derived ETag and the given ``Cache-Control``; answer
        the request's ``If-None-Match`` with an empty ``304`` when
        the representation is unchanged (a CDN revalidation costs
        headers, not payload bytes)."""
        etag = f'"{crc32_hex(body)}-{len(body)}"'
        headers = list(headers) + [
            ("ETag", etag), ("Cache-Control", cache_control),
        ]
        if self.headers.get("If-None-Match") == etag:
            get_registry().counter(
                "tpudas_serve_not_modified_total",
                "conditional GETs answered 304 from a matching ETag",
            ).inc()
            self._send(304, b"", content_type, headers)
            return 304
        self._send(200, body, content_type, headers)
        return 200

    # -- routing -------------------------------------------------------
    def _resolve_mount(self, path):
        """(mount_or_None, endpoint, stream_id_or_None): strips the
        ``/s/<stream_id>`` prefix to the mount it names.  ``mount``
        is None for an unknown stream id (404) — and for bare
        endpoints on a fleet-only server with no root folder."""
        endpoint = path.rstrip("/") or "/"
        if endpoint == "/fleet/healthz":
            return None, endpoint, None
        if endpoint.startswith("/s/"):
            sid, _, rest = endpoint[3:].partition("/")
            return (
                self.server.mounts.get(sid),
                "/" + rest.rstrip("/"),
                sid,
            )
        return self.server.mount, endpoint, None

    def do_GET(self):  # noqa: N802 - stdlib handler contract
        parts = urllib.parse.urlsplit(self.path)
        mount, endpoint, stream_id = self._resolve_mount(parts.path)
        reg = get_registry()
        t_start = time.perf_counter()
        status = 500
        gated = endpoint in _DATA_ENDPOINTS
        if gated and not self.server.gate.try_enter():
            reg.counter(
                "tpudas_serve_shed_total",
                "data-plane requests shed with 503 (admission gate "
                "full)",
            ).inc()
            self._send_json(
                503,
                {"error": "server saturated, retry later"},
                headers=(("Retry-After", "1"),),
            )
            self._account(reg, endpoint, 503, t_start)
            return
        try:
            if (
                mount is not None
                and mount.remote is not None
                and gated
            ):
                # rate-limited manifest probe (min_refresh_s); a cold
                # tier outage flips the remote stale and the mirror
                # keeps serving (RESILIENCE.md "Cold tier down")
                mount.remote.refresh()
            with span(
                "serve.request", endpoint=endpoint,
                stream=stream_id or "",
            ):
                status = self._dispatch(
                    mount, endpoint, _params(parts.query), stream_id
                )
        except ValueError as exc:
            status = 400
            self._send_json(400, {"error": str(exc)[:300]})
        except Exception as exc:
            status = 500
            reg.counter(
                "tpudas_serve_errors_total",
                "requests that failed with an internal error",
                labelnames=("endpoint",),
            ).inc(endpoint=endpoint)
            log_event(
                "serve_request_failed",
                endpoint=endpoint,
                error=f"{type(exc).__name__}: {str(exc)[:300]}",
            )
            self._send_json(
                500, {"error": f"{type(exc).__name__}: {str(exc)[:300]}"}
            )
        finally:
            if gated:
                self.server.gate.leave()
            self._account(reg, endpoint, status, t_start)

    def _account(self, reg, endpoint, status, t_start):
        reg.counter(
            "tpudas_serve_requests_total",
            "HTTP requests served, by endpoint and status",
            labelnames=("endpoint", "status"),
        ).inc(endpoint=endpoint, status=status)
        reg.histogram(
            "tpudas_serve_request_seconds",
            "request latency by endpoint",
            labelnames=("endpoint",),
        ).observe(time.perf_counter() - t_start, endpoint=endpoint)

    def _dispatch(
        self, mount, endpoint: str, params: dict, stream_id=None
    ) -> int:
        if endpoint == "/fleet/healthz":
            return self._fleet_healthz()
        if endpoint == "/metrics" and stream_id is None:
            # process-wide (in a fleet: already merged over streams)
            return self._metrics()
        if stream_id is not None and mount is None:
            self._send_json(
                404,
                {"error": f"unknown stream {stream_id!r}",
                 "streams": sorted(self.server.mounts)},
            )
            return 404
        if endpoint == "/trace":
            return self._trace(mount, params, stream_id)
        if endpoint == "/slo":
            return self._slo(mount, params, stream_id)
        if endpoint == "/devprof":
            return self._devprof(params)
        if endpoint == "/profile":
            return self._profile(params)
        if endpoint in (*_DATA_ENDPOINTS, "/healthz", "/live") and (
            mount is None
        ):
            # fleet-only server, bare endpoint: point at the routes
            self._send_json(
                404,
                {"error": "no root folder mounted; use "
                          "/s/<stream_id>" + endpoint,
                 "streams": sorted(self.server.mounts)},
            )
            return 404
        if endpoint == "/healthz":
            return self._healthz(mount)
        if endpoint == "/live":
            return self._live(mount, params, stream_id)
        if endpoint == "/query":
            return self._query(mount, params, waterfall=False)
        if endpoint == "/waterfall":
            return self._query(mount, params, waterfall=True)
        if endpoint == "/events":
            return self._events(mount, params)
        if endpoint == "/tile":
            return self._tile(mount, params)
        self._send_json(404, {"error": f"unknown endpoint {endpoint!r}"})
        return 404

    # -- live push plane (ISSUE 19) ------------------------------------
    def _live(self, mount, params: dict, stream_id=None) -> int:
        """``GET /live`` / ``GET /s/<id>/live`` — the SSE push
        subscription (snapshot-then-delta, see SERVING.md "Live
        subscriptions").  Deliberately NOT behind the admission gate:
        a subscription is open-ended, and thousands of them must not
        starve the bounded data plane — their cost is bounded by the
        hub's per-client queues instead."""
        from tpudas.live.hub import find_hub
        from tpudas.live.sse import serve_live

        hub = find_hub(
            stream_id if stream_id is not None else mount.stream_id,
            mount.folder,
        )
        if hub is None:
            self._send_json(
                503,
                {"error": "no live producer attached (run the stream "
                          "with live=True / TPUDAS_LIVE=1, or point "
                          "this server at it with live_bridge=)"},
                headers=(("Retry-After", "5"),),
            )
            return 503
        return serve_live(self, hub, mount, params)

    # -- control plane -------------------------------------------------
    @staticmethod
    def _store_block(mount):
        """The ``store`` health block for a remote-pyramid mount:
        refresh state, generation, and the read-through cache's
        hit/stale/degraded snapshot — plus whether the mount is
        currently degraded (cold tier unreachable, serving
        stale-but-verified bytes)."""
        if mount is None or mount.remote is None:
            return None, False
        snap = mount.remote.snapshot()
        degraded = bool(
            snap.get("stale")
            or (snap.get("cache") or {}).get("degraded")
        )
        snap["status"] = "degraded" if degraded else "ok"
        return snap, degraded

    def _healthz(self, mount) -> int:
        payload = read_health(mount.folder)
        store_block, store_degraded = self._store_block(mount)
        if payload is None:
            if store_block is not None:
                # a stateless serving replica has no realtime health
                # snapshot; its liveness IS the store plane's
                self._send_json(
                    200,
                    {"status": (
                        "degraded" if store_degraded else "ok"
                    ),
                     "detail": "serving replica (no local realtime "
                               "health snapshot)",
                     "store": store_block},
                )
                return 200
            self._send_json(
                503,
                {"status": "unknown",
                 "detail": "no health snapshot yet (is the stream "
                           "running with TPUDAS_HEALTH=1?)"},
            )
            return 503
        body = dict(payload)
        body["status"] = (
            "degraded" if payload.get("degraded") or store_degraded
            else "ok"
        )
        if store_block is not None:
            body["store"] = store_block
        self._send_json(200, body)
        return 200

    def _fleet_healthz(self) -> int:
        """Aggregate health over every mounted stream: the fleet
        operator's one-stop liveness view.  Per-stream entries use
        the SAME health→entry mapping and worst-first status ranking
        as ``tpudas.obs.collect`` (``ok`` < ``at_risk`` < ``unknown``
        < ``degraded``/``violating``), folding in each stream's
        freshness-SLO status — so this endpoint and
        ``tools/obs_report.py`` can never disagree; overall is ``ok``
        only when every stream's health AND SLO are.  Always 200 when
        at least one stream is mounted — a degraded fleet must still
        be inspectable — and 503 with no mounts at all."""
        mounts = self.server.mounts
        if not mounts:
            self._send_json(
                503,
                {"status": "unknown",
                 "detail": "no streams mounted (fleet routes need "
                           "DASServer(streams=...) or .for_fleet)"},
            )
            return 503
        from tpudas.obs.collect import (
            SLOPolicy,
            health_entry,
            worst_status,
        )

        policy = SLOPolicy()
        streams = {}
        counts = {"ok": 0, "degraded": 0, "unknown": 0}
        slo_counts: dict = {}
        for sid in sorted(mounts):
            payload = read_health(mounts[sid].folder)
            entry = health_entry(payload)
            status = entry["status"]
            entry["slo"] = _slo_status_cached(
                mounts[sid], policy, health=payload
            )
            # device telemetry column (ISSUE 17): bound classification
            # + roofline utilization from the stream's flight ring
            dev = _devprof_entry_cached(mounts[sid])
            if dev is not None:
                entry["devprof"] = dev
            slo_counts[entry["slo"]["status"]] = (
                slo_counts.get(entry["slo"]["status"], 0) + 1
            )
            counts[status] += 1
            streams[sid] = entry
        # the SAME worst-first ranking over health AND SLO statuses as
        # tpudas.obs.collect.fleet_rollup — the HTTP monitor and
        # tools/obs_report.py must never disagree about the fleet
        overall = worst_status(
            [e["status"] for e in streams.values()]
            + [e["slo"]["status"] for e in streams.values()]
        )
        self._send_json(
            200,
            {"status": overall, "streams": streams, "counts": counts,
             "slo_counts": slo_counts},
        )
        return 200

    def _metrics(self) -> int:
        text = get_registry().to_prometheus()
        self._send(
            200, text.encode(), "text/plain; version=0.0.4; charset=utf-8"
        )
        return 200

    def _trace(self, mount, params: dict, stream_id=None) -> int:
        """Recent spans (and other flight records), filterable — the
        operator's post-hoc "what was the stream doing" view (ISSUE
        13).  A mounted folder with a flight ring serves its
        crash-surviving on-disk records; otherwise the process's
        in-memory span ring answers.  Control plane: bypasses the
        admission gate like ``/healthz`` — tracing a saturated server
        is the point."""
        from tpudas.obs.flight import read_flight, segment_paths
        from tpudas.obs.trace import get_spans

        kind = params.get("kind", "span")
        name = params.get("name") or None
        limit = int(params.get("limit", 256))
        if limit < 1:
            raise ValueError(f"limit must be positive, got {limit}")
        limit = min(limit, 5000)
        with span("serve.trace", stream=stream_id or ""):
            if mount is not None and segment_paths(mount.folder):
                records = read_flight(
                    mount.folder, kind=kind or None, name=name,
                    limit=limit,
                )
                source = "flight"
            else:
                records = get_spans(name)
                if kind and kind != "span":
                    records = []
                records = records[-limit:]
                source = "ring"
        self._send_json(
            200,
            {"source": source, "kind": kind or None, "name": name,
             "count": len(records), "records": records},
        )
        return 200

    def _slo(self, mount, params: dict, stream_id=None) -> int:
        """Per-stream freshness SLO status (tpudas.obs.collect): the
        current head-lag vs target plus the error-budget burn over
        the flight ring's recent rounds.  Bare on a fleet server =
        every mounted stream; scoped = one stream."""
        from tpudas.obs.collect import SLOPolicy, worst_status

        window = int(params.get("window", 200))
        if window < 1:
            raise ValueError(f"window must be positive, got {window}")
        objective = float(params.get("objective", 0.99))
        if not 0.0 < objective <= 1.0:
            raise ValueError(
                f"objective must be in (0, 1], got {objective}"
            )
        policy = SLOPolicy(
            head_lag_target_s=(
                float(params["target"]) if "target" in params else None
            ),
            objective=objective,
            window=window,
        )
        with span("serve.slo", stream=stream_id or ""):
            if stream_id is not None or (
                mount is not None and not self.server.mounts
            ):
                payload = _slo_status_cached(mount, policy)
            else:
                streams = {
                    sid: _slo_status_cached(m, policy)
                    for sid, m in sorted(self.server.mounts.items())
                }
                if mount is not None:
                    streams["."] = _slo_status_cached(mount, policy)
                payload = {
                    "status": worst_status(
                        e["status"] for e in streams.values()
                    ),
                    "streams": streams,
                }
        self._send_json(200, payload)
        return 200

    def _devprof(self, params: dict) -> int:
        """Device telemetry snapshot (ISSUE 17): per-kernel launch and
        device-execute accounting, compile / recompile-storm state,
        one-time cost captures and the live launch-bound vs
        compute-bound classification per stream.  Process-wide (the
        device is shared) and control plane: bypasses the admission
        gate — profiling a saturated server is the point."""
        from tpudas.obs import devprof

        calibrate = str(params.get("calibrate", "1")).lower() not in (
            "0", "false", "no",
        )
        self._send_json(
            200, devprof.devprof_snapshot(calibrate=calibrate)
        )
        return 200

    def _profile(self, params: dict) -> int:
        """Time-boxed ``jax.profiler`` trace into TPUDAS_PROFILE_DIR
        without restarting the stream: ``?seconds=N`` starts one,
        bare ``/profile`` reports status.  501 when the profiler is
        unavailable in this runtime, 409 while a capture is already
        running, 503 when disk pressure sheds the write."""
        from tpudas.obs import devprof

        if "seconds" not in params:
            self._send_json(200, devprof.profile_status())
            return 200
        if not devprof.profiler_available():
            self._send_json(
                501,
                {"error": "jax.profiler is unavailable in this "
                          "runtime; install a jax build with profiler "
                          "support or inspect /devprof instead"},
            )
            return 501
        seconds = float(params["seconds"])
        out_dir = params.get("dir") or None
        try:
            info = devprof.start_profile(seconds, out_dir=out_dir)
        except RuntimeError as exc:
            status = 409 if "already" in str(exc).lower() else 503
            self._send_json(status, {"error": str(exc)[:300]})
            return status
        self._send_json(200, info)
        return 200

    # -- data plane ----------------------------------------------------
    def _events(self, mount, params: dict) -> int:
        """The detection query plane: integrity-verified ledger events
        (and optionally score rows) filtered by time/channel window,
        score floor, operator and kind."""
        t0_ns = (
            int(np.datetime64(_parse_time(params["t0"]), "ns")
                .astype(np.int64))
            if "t0" in params else None
        )
        t1_ns = (
            int(np.datetime64(_parse_time(params["t1"]), "ns")
                .astype(np.int64))
            if "t1" in params else None
        )
        c0 = int(params["c0"]) if "c0" in params else None
        c1 = int(params["c1"]) if "c1" in params else None
        min_score = (
            float(params["min_score"]) if "min_score" in params else None
        )
        op = params.get("op")
        kind = params.get("kind")
        limit = int(params.get("limit", _DEFAULT_EVENTS_LIMIT))
        if limit < 1:
            raise ValueError(f"limit must be positive, got {limit}")
        scores_limit = int(
            params.get("scores_limit", _DEFAULT_SCORES_LIMIT)
        )
        if scores_limit < 1:
            raise ValueError(
                f"scores_limit must be positive, got {scores_limit}"
            )
        with span("serve.events"):
            events = _load_events_cached(mount)
            total = len(events)
            picked = []
            # scan newest-first so the cap keeps the events happening
            # NOW (the scores cap's discipline); chronological order
            # is restored below
            for ev in reversed(events):
                if t0_ns is not None and int(ev.get("t_ns", 0)) < t0_ns:
                    continue
                if t1_ns is not None and int(ev.get("t_ns", 0)) >= t1_ns:
                    continue
                ch = int(ev.get("channel", -1))
                if c0 is not None and ch < c0:
                    continue
                if c1 is not None and ch > c1:
                    continue
                if min_score is not None and float(
                    ev.get("score", 0.0)
                ) < min_score:
                    continue
                if op is not None and ev.get("op") != op:
                    continue
                if kind is not None and ev.get("kind") != kind:
                    continue
                picked.append(ev)
                if len(picked) >= limit:
                    break
            picked.reverse()
            payload = {
                "events": picked,
                "count": len(picked),
                "ledger_events": total,
            }
            if params.get("scores") == "1":
                try:
                    store = _open_score_store_cached(mount)
                except Exception as exc:
                    # an unreconcilable score store (the fsck's reset
                    # case) must degrade the scores track, not fail a
                    # response whose events were perfectly readable
                    log_event(
                        "serve_events_scores_unavailable",
                        error=f"{type(exc).__name__}: {str(exc)[:200]}",
                    )
                    store = None
                if store is None:
                    payload["scores"] = None
                else:
                    s_t, s_v = store.read(t0_ns, t1_ns)
                    rows_total = int(s_t.shape[0])
                    if rows_total > scores_limit:
                        # bound the response: keep the NEWEST rows in
                        # the window (what a live dashboard wants)
                        s_t = s_t[-scores_limit:]
                        s_v = s_v[-scores_limit:]
                    vals = s_v
                    ch_lo = 0
                    if c0 is not None or c1 is not None:
                        ch_lo = max(0, c0 or 0)
                        ch_hi = (
                            min(vals.shape[1] - 1, c1)
                            if c1 is not None else vals.shape[1] - 1
                        )
                        vals = vals[:, ch_lo:ch_hi + 1]
                    payload["scores"] = {
                        "times_ns": [int(t) for t in s_t],
                        "channel0": int(ch_lo),
                        "values": _json_safe(vals),
                        "rows_total": rows_total,
                        "truncated": rows_total > scores_limit,
                    }
        reg = get_registry()
        reg.counter(
            "tpudas_serve_events_queries_total",
            "/events queries answered from the verified ledger",
        ).inc()
        # events are live mutable state: origin-only, but still ETag-
        # revalidatable (a polling dashboard's unchanged ledger costs
        # headers, not the serialized event list)
        body = (json.dumps(payload, indent=1) + "\n").encode()
        return self._send_cacheable(
            body, "application/json",
            [("X-Tpudas-Events-Total", total)], _MUTABLE_CC,
        )

    def _tile(self, mount, params: dict) -> int:
        """One pyramid tile by address (``level``, ``idx``) — the
        CDN-shaped read path (ISSUE 11).  A COMPLETED tile is
        immutable by construction, so it ships with a strong ETag and
        ``Cache-Control: immutable``: an edge cache absorbs every
        repeat read forever.  The trailing PARTIAL tile is the
        mutable hot path and stays ``no-cache`` (revalidated at
        origin per request).  Under a compressed store a client that
        advertises ``Accept-Encoding: x-tpt`` gets the stored
        :mod:`tpudas.codec` blob verbatim (zero-copy off disk,
        self-describing — decode client-side); everyone else gets
        decoded raw ``.npy`` bytes."""
        from tpudas.serve.tiles import AGGS

        if "level" not in params or "idx" not in params:
            raise ValueError(
                "level and idx query parameters are required"
            )
        level = int(params["level"])
        idx = int(params["idx"])
        if level < 0 or idx < 0:
            raise ValueError("level and idx must be non-negative")
        store = mount.engine._refresh_store()
        if store is None or store.head_ns is None:
            self._send_json(
                404, {"error": "no tile pyramid in this folder"}
            )
            return 404
        n_level = store.n(level) if level < store.n_levels else 0
        valid = min(store.tile_len, n_level - idx * store.tile_len)
        if valid <= 0:
            self._send_json(
                404,
                {"error": f"tile L{level}/{idx} is beyond the "
                          f"pyramid head",
                 "levels": list(store.levels),
                 "tile_len": int(store.tile_len)},
            )
            return 404
        if mount.remote is not None and valid == store.tile_len:
            # materialize the addressed completed-tile object into the
            # mirror (read-through cached; no-op when already local —
            # the partial head tile serves from mirrored tails)
            mount.remote._fetch_tile(store, level, idx)
        headers = [
            ("X-Tpudas-Level", level),
            ("X-Tpudas-Tile", idx),
            ("X-Tpudas-Valid-Rows", valid),
            ("X-Tpudas-Codec", store.codec or "raw"),
            ("Vary", "Accept-Encoding"),
        ]
        if valid == store.tile_len:
            path = store.resolve_tile_path(level, idx)
            if path is None:
                raise FileNotFoundError(
                    f"manifest references tile L{level}/{idx} but no "
                    "tile file exists (corrupt store)"
                )
            with open(path, "rb") as fh:
                blob = fh.read()
            # verify BEFORE the immutable header: a torn/bit-rotted
            # tile served with max-age=31536000 poisons a CDN for a
            # year — every other read path takes the corrupt-store
            # ladder, this one must too
            if path.endswith(TILE_BLOB_SUFFIX):
                from tpudas.codec import verify_tile_blob
                from tpudas.serve.tiles import CorruptStoreError

                if verify_tile_blob(blob) != "ok":
                    raise CorruptStoreError(
                        f"tile L{level}/{idx} failed its embedded "
                        "crc32 check — run tools/fsck.py to rebuild"
                    )
                if self._accepts(_TPT_CODING):
                    # stored compressed blob, verbatim: the cheapest
                    # possible origin read, and what a CDN should cache
                    return self._send_cacheable(
                        blob, "application/x-tpudas-tile",
                        headers + [("Content-Encoding", _TPT_CODING)],
                        _IMMUTABLE_CC,
                    )
                arr = decode_tile(blob)
                buf = io.BytesIO()
                np.save(buf, np.ascontiguousarray(arr))
                body = buf.getvalue()
            else:
                # raw .npy bytes ARE the representation — after the
                # sidecar-crc gate (raises CorruptStoreError -> 500)
                store._verify_tile(path)
                body = blob
            return self._send_cacheable(
                body, "application/x-npy", headers, _IMMUTABLE_CC
            )
        # the growing head tile: serve its current rows, never cache
        tile = store._load_tile(level, idx)
        arr = (
            tile["mean"] if level == 0
            else np.stack([tile[agg] for agg in AGGS], axis=0)
        )
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr))
        return self._send_cacheable(
            buf.getvalue(), "application/x-npy", headers, _MUTABLE_CC
        )

    def _query(self, mount, params: dict, waterfall: bool) -> int:
        if "t0" not in params or "t1" not in params:
            raise ValueError("t0 and t1 query parameters are required")
        t0 = _parse_time(params["t0"])
        t1 = _parse_time(params["t1"])
        dist = None
        if "d0" in params or "d1" in params:
            dist = (
                float(params["d0"]) if "d0" in params else None,
                float(params["d1"]) if "d1" in params else None,
            )
        agg = params.get("agg", "mean")
        if waterfall:
            max_samples = int(params.get("max_px", 1024))
            resolution = None
        else:
            max_samples = (
                int(params["max_samples"]) if "max_samples" in params
                else None
            )
            resolution = (
                float(params["resolution"]) if "resolution" in params
                else None
            )
        result = mount.engine.query(
            t0, t1, distance=dist, resolution=resolution,
            max_samples=max_samples, agg=agg,
        )
        headers = [
            ("X-Tpudas-Level", result.level),
            ("X-Tpudas-Step-Ns", result.step_ns),
            ("X-Tpudas-Agg", result.agg),
            ("X-Tpudas-Source", result.source),
            ("X-Tpudas-Samples", result.n_samples),
            ("X-Tpudas-Channels", result.distance.size),
        ]
        if result.n_samples:
            headers.append(
                ("X-Tpudas-T0-Ns",
                 int(result.times[0].astype("datetime64[ns]")
                     .astype(np.int64)))
            )
        if waterfall:
            from tpudas.viz.waterfall import _symmetric_clip

            lo, hi = _symmetric_clip(result.data)
            headers += [
                ("X-Tpudas-Clim-Lo", repr(float(lo))),
                ("X-Tpudas-Clim-Hi", repr(float(hi))),
            ]
        cache_control = (
            _IMMUTABLE_CC if result.immutable else _MUTABLE_CC
        )
        if params.get("format", "npy") == "json":
            body = (json.dumps(
                {
                    "times_ns": [
                        int(t) for t in
                        result.times.astype("datetime64[ns]")
                        .astype(np.int64)
                    ],
                    "distance": [float(d) for d in result.distance],
                    "data": _json_safe(result.data),
                    "level": result.level,
                    "step_ns": result.step_ns,
                    "agg": result.agg,
                    "source": result.source,
                },
                indent=1,
            ) + "\n").encode()
            content_type = "application/json"
        else:
            buf = io.BytesIO()
            np.save(buf, np.ascontiguousarray(result.data))
            body = buf.getvalue()
            content_type = "application/x-npy"
        body, enc_headers = self._maybe_deflate(body)
        return self._send_cacheable(
            body, content_type, headers + enc_headers, cache_control
        )


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # the stdlib default listen backlog (5) makes a thundering herd
    # pay 1-second SYN retransmits long before the admission gate
    # even sees the request; shedding is the GATE's job, done with an
    # explicit 503, not silent kernel queue drops
    request_queue_size = 128

    def __init__(self, addr, mount, mounts, gate, reuse_port=False):
        self.mount = mount  # root _Mount or None (fleet-only server)
        self.mounts = dict(mounts)  # stream_id -> _Mount
        self.gate = gate
        # SO_REUSEPORT lets N worker PROCESSES bind the same port and
        # have the kernel load-balance accepted connections across
        # them — the tpudas.serve.pool horizontal-scale mechanism
        # (the crash-only tile format already makes concurrent
        # readers safe, so workers share the store read-only)
        self._reuse_port = bool(reuse_port)
        super().__init__(addr, _Handler)

    def server_bind(self):
        if self._reuse_port:
            if not hasattr(socket, "SO_REUSEPORT"):
                raise OSError(
                    "SO_REUSEPORT is not available on this platform; "
                    "run single-process or front workers with a "
                    "balancer"
                )
            self.socket.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
            )
        super().server_bind()

    @property
    def folder(self):  # legacy accessor (pre-fleet single-folder API)
        return None if self.mount is None else self.mount.folder


class DASServer:
    """Lifecycle wrapper: background thread + context manager.

    ``port=0`` binds an ephemeral port (tests); :attr:`base_url` gives
    the bound address either way.

    ``folder`` mounts one output folder on the bare endpoints
    (``/query``, ``/healthz``, ...); ``streams`` (a
    ``{stream_id: folder}`` mapping) additionally mounts each stream
    at ``/s/<stream_id>/...`` and enables ``/fleet/healthz``.  Either
    may be omitted; :meth:`for_fleet` builds the ``streams`` mapping
    from a fleet root's directory layout.  All mounts share one
    admission gate and the one process registry.

    ``store_url`` (+ ``store_prefix``) mounts an OBJECT-STORE pyramid
    instead of (or on top of) a local folder: the server becomes a
    stateless serving replica that hydrates a local mirror through an
    NVMe read-through cache (``cache_dir``/``cache_bytes``), probes
    the remote manifest at most every ``store_refresh_s`` seconds
    before data queries, and keeps serving the mirror (flagged
    ``degraded`` in ``/healthz``'s ``store`` block) when the cold
    tier is unreachable.  See SERVING.md "Object-store serving".
    A ``replica:urlA,urlB,...`` store URL serves through a
    :class:`~tpudas.store.replica.ReplicatedStore` — reads fail over
    primary → mirrors → the cache's stale-but-verified rung, and the
    ``store`` block of ``/healthz`` grows a ``replication`` entry
    (mirror list, handoff backlog, failover/divergence counts, last
    scrub).  See SERVING.md "Multi-region serving".
    """

    def __init__(self, folder=None, host="127.0.0.1", port=0,
                 max_inflight=_DEFAULT_MAX_INFLIGHT, cache_tiles=256,
                 engine=None, streams=None, reuse_port=False,
                 store_url=None, store_prefix="", cache_dir=None,
                 cache_bytes=None, store_refresh_s=1.0,
                 live_bridge=None):
        if folder is None and not streams and store_url is None:
            raise ValueError(
                "DASServer needs a folder, streams, or a store_url"
            )
        self.remote = None
        if store_url is not None:
            # stateless serving replica (ISSUE 18): hydrate a local
            # mirror + NVMe read-through cache from the object store;
            # `folder` (when given) IS the mirror directory, otherwise
            # a private temp dir — either can be wiped freely
            import tempfile

            from tpudas.store import (
                ReadThroughCache,
                RemotePyramid,
                store_from_url,
            )

            base = (
                str(cache_dir) if cache_dir is not None
                else tempfile.mkdtemp(prefix="tpudas-serve-store-")
            )
            cache_kwargs = (
                {} if cache_bytes is None
                else {"max_bytes": int(cache_bytes)}
            )
            cache = ReadThroughCache(
                os.path.join(base, "cache"), **cache_kwargs
            )
            mirror = (
                str(folder) if folder is not None
                else os.path.join(base, "mirror")
            )
            self.remote = RemotePyramid(
                store_from_url(store_url), store_prefix, cache,
                mirror, min_refresh_s=float(store_refresh_s),
            )
            self.remote.refresh(force=True)
            folder = mirror
        self.folder = None if folder is None else str(folder)
        mount = (
            None if folder is None
            else _Mount(folder, cache_tiles=cache_tiles, engine=engine,
                        remote=self.remote)
        )
        mounts = {}
        for sid, sfolder in (streams or {}).items():
            sid = str(sid)
            mounts[sid] = _Mount(
                sfolder, stream_id=sid, cache_tiles=cache_tiles,
                engine=engine,
            )
        # legacy attribute: the root mount's engine (None on a
        # fleet-only server); per-stream engines live on the mounts
        self.query_engine = None if mount is None else mount.engine
        self.mounts = mounts
        self._httpd = _Server(
            (host, int(port)), mount, mounts,
            _AdmissionGate(max_inflight), reuse_port=reuse_port,
        )
        self._thread = None
        # live push plane (ISSUE 19): when the producer runs in a
        # DIFFERENT process (ServePool worker, remote replica), the
        # local hub registry is empty — `live_bridge` names the
        # producer's LiveBridge address and a BridgeSubscriber feeds
        # mirrored hubs that `/live` then serves from
        self.live_bridge = live_bridge
        self._bridge_sub = None

    @classmethod
    def for_fleet(cls, root, **kwargs):
        """A server over a fleet root: every non-hidden subdirectory
        is mounted as a stream at ``/s/<name>/...`` (the
        ``FleetEngine`` layout — see FLEET.md).  ``folder=`` may be
        passed through to also mount a root folder on the bare
        endpoints."""
        from tpudas.integrity.audit import fleet_stream_dirs

        streams = dict(fleet_stream_dirs(root))
        if not streams:
            raise ValueError(
                f"no stream folders found under fleet root {root!r}"
            )
        return cls(streams=streams, **kwargs)

    @property
    def address(self):
        return self._httpd.server_address[:2]

    @property
    def base_url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def _start_bridge(self) -> None:
        if self.live_bridge and self._bridge_sub is None:
            from tpudas.live.sse import BridgeSubscriber

            self._bridge_sub = BridgeSubscriber(self.live_bridge).start()

    def start(self) -> "DASServer":
        self._start_bridge()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpudas-serve",
            daemon=True,
        )
        self._thread.start()
        log_event("serve_started", url=self.base_url, folder=self.folder)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._bridge_sub is not None:
            self._bridge_sub.stop()
            self._bridge_sub = None

    def __enter__(self) -> "DASServer":
        return self.start()

    def __exit__(self, *exc_info):
        self.stop()
        return False


def start_server(folder, **kwargs) -> DASServer:
    """Start a :class:`DASServer` on a background thread; returns it
    (use as a context manager or call ``.stop()``)."""
    return DASServer(folder, **kwargs).start()


def serve_forever(folder, host="0.0.0.0", port=8000, fleet=False,
                  **kwargs) -> None:
    """Blocking operator entry point (Ctrl-C to stop).  ``fleet=True``
    treats ``folder`` as a fleet root and mounts every stream at
    ``/s/<stream_id>/...`` (plus ``/fleet/healthz``)."""
    if fleet:
        server = DASServer.for_fleet(folder, host=host, port=port,
                                     **kwargs)
        print(
            f"tpudas.serve listening on {server.base_url} over fleet "
            f"root {folder} (streams: {', '.join(sorted(server.mounts))})"
        )
    else:
        server = DASServer(folder, host=host, port=port, **kwargs)
        print(
            f"tpudas.serve listening on {server.base_url} over {folder}"
        )
    try:
        server._start_bridge()
        server._httpd.serve_forever()
    except KeyboardInterrupt:
        print("shutting down")
    finally:
        server._httpd.server_close()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Serve processed DAS output over HTTP "
                    "(/query /waterfall /healthz /metrics; with "
                    "--fleet also /s/<stream>/... and /fleet/healthz)"
    )
    ap.add_argument("folder",
                    help="processed output folder (or, with --fleet, "
                         "the fleet root whose subdirectories are the "
                         "streams)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-inflight", type=int,
                    default=_DEFAULT_MAX_INFLIGHT)
    ap.add_argument("--cache-tiles", type=int, default=256)
    ap.add_argument("--fleet", action="store_true",
                    help="serve a fleet root: mount every "
                         "<root>/<stream_id>/ at /s/<stream_id>/...")
    ap.add_argument("--store-url", default=None,
                    help="serve a remote pyramid from this object "
                         "store (file:///path, s3://bucket/..., "
                         "fake:tag); FOLDER becomes the local mirror")
    ap.add_argument("--store-prefix", default="",
                    help="stream prefix inside the store")
    ap.add_argument("--cache-dir", default=None,
                    help="NVMe read-through cache directory "
                         "(default: private temp dir)")
    ap.add_argument("--cache-bytes", type=int, default=None,
                    help="read-through cache budget in bytes")
    ap.add_argument("--live-bridge", default=None,
                    help="subscribe to a producer's live bridge at "
                         "host:port (TPUDAS_LIVE_BRIDGE on the "
                         "producer) so /live serves its streams")
    args = ap.parse_args(argv)
    kwargs = {}
    if args.live_bridge:
        kwargs["live_bridge"] = args.live_bridge
    if args.store_url:
        if args.fleet:
            ap.error("--store-url and --fleet are mutually exclusive")
        kwargs.update(
            store_url=args.store_url, store_prefix=args.store_prefix,
            cache_dir=args.cache_dir, cache_bytes=args.cache_bytes,
        )
    serve_forever(
        args.folder, host=args.host, port=args.port,
        max_inflight=args.max_inflight, cache_tiles=args.cache_tiles,
        fleet=args.fleet, **kwargs,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
