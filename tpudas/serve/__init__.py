"""tpudas.serve — the read side of the streaming stack.

The pipeline produces low-frequency output continuously
(tpudas.proc.streaming); this package makes that output *queryable* at
interactive latency without re-reading raw files:

- :mod:`tpudas.serve.tiles` — an incremental multi-resolution pyramid
  (mean/min/max) over the processed output, appended round-by-round
  beside the stream carry, crash-only like the carry itself;
- :mod:`tpudas.serve.query` — time x distance window reads that pick
  the coarsest pyramid level satisfying a requested resolution, backed
  by an LRU tile cache with single-flight request coalescing and a
  full-resolution file fallback for windows older than the pyramid;
- :mod:`tpudas.serve.http` — a zero-dependency threaded HTTP server
  (``/query``, ``/waterfall``, ``/tile``, ``/events``, ``/healthz``,
  ``/metrics``) with a bounded admission gate that sheds load with
  503 + Retry-After, strong ETags/conditional GET, and
  immutable-tile ``Cache-Control`` for CDN absorption (ISSUE 11).
  ``/events`` is the detection query plane over the
  :mod:`tpudas.detect` events ledger and score tiles.
- :mod:`tpudas.serve.pool` — the horizontal-scale tier: N server
  processes over one read-only store sharing a single
  ``SO_REUSEPORT`` data port, merged ``/metrics`` + aggregate
  ``/healthz`` control plane (``tools/serve_pool.py``).

Completed tiles are stored raw or through the pluggable
:mod:`tpudas.codec` compressed tile container
(``codec=``/``TPUDAS_CODEC=``).  See SERVING.md for the pyramid and
blob formats, endpoint reference, CDN recipe and the operator
runbook.
"""

from tpudas.serve.query import QueryEngine, QueryResult
from tpudas.serve.tiles import TileStore, rebuild_pyramid, sync_pyramid

__all__ = [
    "QueryEngine",
    "QueryResult",
    "ServePool",
    "TileStore",
    "rebuild_pyramid",
    "sync_pyramid",
    "serve_forever",
    "start_server",
]


def ServePool(*args, **kwargs):  # noqa: N802 - class-shaped factory
    """Lazy re-export of :class:`tpudas.serve.pool.ServePool` (keeps
    ``import tpudas.serve`` free of multiprocessing/http.server)."""
    from tpudas.serve.pool import ServePool as _Pool

    return _Pool(*args, **kwargs)


def start_server(*args, **kwargs):
    """Lazy re-export of :func:`tpudas.serve.http.start_server` (keeps
    ``import tpudas.serve`` free of the http.server import)."""
    from tpudas.serve.http import start_server as _start

    return _start(*args, **kwargs)


def serve_forever(*args, **kwargs):
    """Lazy re-export of :func:`tpudas.serve.http.serve_forever`."""
    from tpudas.serve.http import serve_forever as _serve

    return _serve(*args, **kwargs)
