"""Horizontally scaled serving: an N-process worker pool over one
shared read-only store (ISSUE 11).

One ``ThreadingHTTPServer`` process saturates around BENCH_pr04's
~120 QPS — python threads share a GIL, so more threads buy
concurrency but not CPU.  The crash-only tile format already makes
concurrent READERS safe (immutable full tiles, atomic tails/manifest
replaces, stat-gated refresh), so horizontal scale is just more
processes over the same bytes:

- **Workers**: N child processes (spawned, so no forked locks), each
  running a full :class:`tpudas.serve.http.DASServer` bound to the
  SAME data port via ``SO_REUSEPORT`` — the kernel load-balances
  accepted connections across the listening sockets, no proxy hop,
  no fd passing.  Each worker additionally binds a private ephemeral
  **control port** for its own ``/metrics``.
- **Pool control plane**: the parent binds ``control_port`` and
  serves ``/metrics`` — every worker's process registry merged into
  one exposition, each sample tagged ``worker="<i>"`` — plus
  ``/healthz`` / ``/pool/healthz``, the aggregate liveness rollup
  (``ok`` only when every worker process is alive and scrapeable).

Per-worker caches are independent by design: a tile decoded in
worker 0 is decoded again on first touch in worker 1.  That is the
stateless-worker property that makes the pool trivially scalable —
the shared cache tier is the CDN/edge cache the immutable-tile HTTP
headers (:mod:`tpudas.serve.http`) are built for, not process memory.

Operator entry point (see also ``tools/serve_pool.py``)::

    python -m tpudas.serve.pool /data/out --port 8000 --workers 8
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.utils.logging import log_event

__all__ = ["ServePool", "merge_prometheus", "main"]

_DEFAULT_WORKERS = 2
_SCRAPE_TIMEOUT_S = 5.0


def has_reuse_port() -> bool:
    """Whether this platform can run the pool at all (Linux/BSD yes;
    the tests skip where it cannot)."""
    return hasattr(socket, "SO_REUSEPORT")


# ---------------------------------------------------------------------------
# prometheus merge

def _label_sample(line: str, worker: str) -> str:
    """One exposition sample line with a ``worker`` label injected
    (first position, so existing labels survive verbatim)."""
    head, _, value = line.rpartition(" ")
    if not head:
        return line
    if "{" in head:
        name, _, rest = head.partition("{")
        return f'{name}{{worker="{worker}",{rest} {value}'
    return f'{head}{{worker="{worker}"}} {value}'


def merge_prometheus(texts: dict) -> str:
    """Merge ``{worker_id: exposition_text}`` into one exposition:
    ``# HELP``/``# TYPE`` metadata deduplicated, every sample tagged
    with its ``worker`` label.  Nothing is summed — cross-worker
    aggregation is the scraper's job (PromQL ``sum without(worker)``),
    and collapsing here would destroy the per-worker balance view the
    pool exists to expose."""
    out: list = []
    seen_meta: set = set()
    for worker in sorted(texts):
        for line in texts[worker].splitlines():
            if line.startswith("#"):
                if line not in seen_meta:
                    seen_meta.add(line)
                    out.append(line)
                continue
            if line.strip():
                out.append(_label_sample(line, str(worker)))
    return "\n".join(out) + ("\n" if out else "")


# ---------------------------------------------------------------------------
# the worker process

def _worker_main(cfg: dict, report_q) -> None:
    """One pool worker: a full DASServer on the SHARED data port
    (``SO_REUSEPORT``) plus a private control DASServer on an
    ephemeral port for per-worker ``/metrics``.  Runs until the
    parent terminates the process (crash-only: workers hold no
    durable state, the store on disk is the only truth)."""
    # serving needs no accelerator; never let a worker grab one
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tpudas.serve.http import DASServer

    kwargs = dict(
        host=cfg["host"],
        max_inflight=cfg["max_inflight"],
        cache_tiles=cfg["cache_tiles"],
    )
    if cfg.get("store_url"):
        # stateless replica workers: each worker hydrates its OWN
        # mirror + read-through cache from the object store — workers
        # share nothing but the store (and whatever CDN sits in front)
        base = cfg.get("cache_dir")
        kwargs.update(
            store_url=cfg["store_url"],
            store_prefix=cfg.get("store_prefix", ""),
            cache_dir=(
                os.path.join(base, f"worker{cfg['index']}")
                if base else None
            ),
            cache_bytes=cfg.get("cache_bytes"),
        )
    dkw = dict(kwargs)
    if cfg.get("live_bridge"):
        # live push plane (ISSUE 19): ONLY the data server subscribes
        # to the producer's bridge — a second subscriber on the
        # control server would double the bridge fan-out for nothing
        # (hub.inject dedups by sequence, but why pay the bytes)
        dkw["live_bridge"] = cfg["live_bridge"]
    if cfg["fleet"]:
        data = DASServer.for_fleet(
            cfg["folder"], port=cfg["port"], reuse_port=True, **dkw
        )
        control = DASServer.for_fleet(cfg["folder"], port=0, **kwargs)
    elif cfg.get("store_url"):
        data = DASServer(
            cfg["folder"], port=cfg["port"], reuse_port=True, **dkw
        )
        # the control plane serves /metrics from THIS process's
        # registry; mount the data server's mirror rather than build
        # a second remote (one store plane per worker)
        control = DASServer(
            data.folder, port=0, host=cfg["host"],
            max_inflight=cfg["max_inflight"],
            cache_tiles=cfg["cache_tiles"],
        )
    else:
        data = DASServer(
            cfg["folder"], port=cfg["port"], reuse_port=True, **dkw
        )
        control = DASServer(cfg["folder"], port=0, **kwargs)
    control.start()
    data.start()
    report_q.put({
        "worker": int(cfg["index"]),
        "pid": os.getpid(),
        "data_port": int(data.address[1]),
        "control_port": int(control.address[1]),
    })
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


# ---------------------------------------------------------------------------
# the pool control plane

class _PoolHandler(BaseHTTPRequestHandler):
    server_version = "tpudas-serve-pool/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log_event("serve_pool_access", line=(fmt % args)[:200])

    def _send(self, status, body: bytes, ctype: str):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib handler contract
        pool = self.server.pool
        path = self.path.split("?")[0].rstrip("/") or "/"
        if path == "/metrics":
            self._send(
                200, pool.merged_metrics().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path in ("/healthz", "/pool/healthz"):
            payload = pool.health()
            self._send(
                200 if payload["status"] == "ok" else 503,
                (json.dumps(payload, indent=1) + "\n").encode(),
                "application/json",
            )
        else:
            self._send(
                404,
                (json.dumps({
                    "error": f"unknown pool endpoint {path!r}",
                    "endpoints": ["/metrics", "/healthz",
                                  "/pool/healthz"],
                }) + "\n").encode(),
                "application/json",
            )


class _PoolControlServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, pool):
        self.pool = pool
        super().__init__(addr, _PoolHandler)


class ServePool:
    """Lifecycle wrapper for the worker pool: context manager or
    ``.start()``/``.stop()``.  ``port=0`` picks a free shared data
    port; ``control_port=0`` an ephemeral control port (tests)."""

    def __init__(self, folder=None, host="127.0.0.1", port=8000,
                 workers=_DEFAULT_WORKERS, control_port=0, fleet=False,
                 max_inflight=8, cache_tiles=256,
                 start_timeout=120.0, max_restarts=5,
                 restart_backoff=0.5, supervise=True,
                 store_url=None, store_prefix="", cache_dir=None,
                 cache_bytes=None, live_bridge=None):
        if not has_reuse_port():
            raise OSError(
                "SO_REUSEPORT is not available on this platform; "
                "the serve pool needs it to share one data port"
            )
        if folder is None and store_url is None:
            raise ValueError("ServePool needs a folder or a store_url")
        self.folder = None if folder is None else str(folder)
        self.host = str(host)
        self.workers = int(workers)
        if self.workers < 1:
            raise ValueError(f"need >= 1 worker, got {workers}")
        self.fleet = bool(fleet)
        self._cfg = dict(
            folder=self.folder, host=self.host, fleet=self.fleet,
            max_inflight=int(max_inflight),
            cache_tiles=int(cache_tiles),
            store_url=store_url, store_prefix=str(store_prefix),
            cache_dir=None if cache_dir is None else str(cache_dir),
            cache_bytes=cache_bytes,
            live_bridge=None if live_bridge is None else str(live_bridge),
        )
        self.port = int(port) or self._pick_port()
        self._control_addr = (self.host, int(control_port))
        self._start_timeout = float(start_timeout)
        self._procs: list = []
        self.worker_info: dict = {}
        self._control = None
        self._control_thread = None
        # worker supervision (ISSUE 12): a dead data-plane worker is
        # respawned (bounded restarts, doubling backoff) instead of
        # permanently shrinking the pool
        self.supervise = bool(supervise)
        self.max_restarts = int(max_restarts)
        self.restart_backoff = float(restart_backoff)
        self._restarts: dict = {}  # index -> {count, backoff, next}
        self._ctx = None
        self._report_q = None
        self._monitor_thread = None
        self._monitor_stop = threading.Event()

    def _pick_port(self) -> int:
        # all workers must share ONE concrete port for SO_REUSEPORT
        # load balancing, so "port 0" is resolved up front (bind,
        # read, release — the narrow reuse race is a test-only cost)
        s = socket.socket()
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((self.host, 0))
            return int(s.getsockname()[1])
        finally:
            s.close()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "ServePool":
        import multiprocessing as mp

        # spawn, not fork: the parent may hold jax/threading state a
        # forked HTTP server must never inherit
        self._ctx = mp.get_context("spawn")
        self._report_q = self._ctx.Queue()
        for i in range(self.workers):
            self._procs.append(self._spawn_worker(i))
        deadline = time.time() + self._start_timeout
        while len(self.worker_info) < self.workers:
            if any(p.exitcode not in (None, 0) for p in self._procs):
                self.stop()
                raise RuntimeError(
                    "a pool worker died during startup (is the "
                    "folder readable? port bindable?)"
                )
            try:
                info = self._report_q.get(timeout=0.25)
                self.worker_info[int(info["worker"])] = info
            except Exception:
                if time.time() > deadline:
                    self.stop()
                    raise RuntimeError(
                        f"pool workers not ready within "
                        f"{self._start_timeout}s"
                    ) from None
        get_registry().gauge(
            "tpudas_serve_pool_workers",
            "serve-pool worker processes currently managed",
        ).set(len(self._procs))
        self._control = _PoolControlServer(self._control_addr, self)
        self._control_thread = threading.Thread(
            target=self._control.serve_forever,
            name="tpudas-serve-pool-control", daemon=True,
        )
        self._control_thread.start()
        if self.supervise:
            self._monitor_stop.clear()
            self._monitor_thread = threading.Thread(
                target=self._monitor,
                name="tpudas-serve-pool-monitor", daemon=True,
            )
            self._monitor_thread.start()
        log_event(
            "serve_pool_started",
            folder=self.folder,
            workers=self.workers,
            port=self.port,
            control_port=self.control_address[1],
        )
        return self

    # -- worker supervision --------------------------------------------
    def _spawn_worker(self, index: int):
        cfg = dict(self._cfg, index=index, port=self.port)
        proc = self._ctx.Process(
            target=_worker_main, args=(cfg, self._report_q),
            name=f"tpudas-serve-worker-{index}", daemon=True,
        )
        proc.start()
        return proc

    def _drain_reports(self) -> None:
        """Pick up (re)spawned workers' port/pid reports so the
        control plane scrapes the live process, not the corpse."""
        import queue as _queue

        while True:
            try:
                info = self._report_q.get_nowait()
            except _queue.Empty:
                return
            self.worker_info[int(info["worker"])] = info

    def _monitor(self) -> None:
        """Supervision loop: respawn dead data-plane workers with
        bounded restarts and doubling backoff — a crashed worker must
        not permanently shrink the pool.  Restarts are counted
        (``tpudas_serve_pool_worker_restarts_total``); a worker past
        ``max_restarts`` stays down and ``/pool/healthz`` reports the
        pool degraded."""
        reg = get_registry()
        while not self._monitor_stop.wait(0.25):
            self._drain_reports()
            for i, proc in enumerate(self._procs):
                if proc is not None and proc.is_alive():
                    continue
                rec = self._restarts.setdefault(
                    i, {
                        "count": 0,
                        "backoff": self.restart_backoff,
                        "next": 0.0,
                    },
                )
                if rec["count"] >= self.max_restarts:
                    continue
                now = time.time()
                if now < rec["next"]:
                    continue
                rec["count"] += 1
                rec["next"] = now + rec["backoff"]
                rec["backoff"] = min(rec["backoff"] * 2.0, 30.0)
                reg.counter(
                    "tpudas_serve_pool_worker_restarts_total",
                    "dead serve-pool workers respawned by the "
                    "supervision loop",
                ).inc()
                log_event(
                    "serve_pool_worker_respawned",
                    worker=i,
                    restart=rec["count"],
                )
                try:
                    self._procs[i] = self._spawn_worker(i)
                except Exception as exc:
                    log_event(
                        "serve_pool_respawn_failed",
                        worker=i,
                        error=f"{type(exc).__name__}: {str(exc)[:200]}",
                    )

    def restart_counts(self) -> dict:
        return {i: r["count"] for i, r in sorted(self._restarts.items())}

    def stop(self) -> None:
        if self._monitor_thread is not None:
            self._monitor_stop.set()
            self._monitor_thread.join(timeout=10)
            self._monitor_thread = None
        if self._control is not None:
            self._control.shutdown()
            self._control.server_close()
            self._control = None
            if self._control_thread is not None:
                self._control_thread.join(timeout=10)
                self._control_thread = None
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=10)
        self._procs = []
        get_registry().gauge(
            "tpudas_serve_pool_workers",
            "serve-pool worker processes currently managed",
        ).set(0)

    def __enter__(self) -> "ServePool":
        return self.start()

    def __exit__(self, *exc_info):
        self.stop()
        return False

    # -- addresses -----------------------------------------------------
    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def control_address(self):
        return self._control.server_address[:2]

    @property
    def control_url(self) -> str:
        host, port = self.control_address
        return f"http://{host}:{port}"

    # -- control-plane payloads ---------------------------------------
    def _scrape(self, info: dict, endpoint: str) -> str:
        url = (
            f"http://{self.host}:{info['control_port']}{endpoint}"
        )
        with urllib.request.urlopen(
            url, timeout=_SCRAPE_TIMEOUT_S
        ) as r:
            return r.read().decode()

    def merged_metrics(self) -> str:
        """Every worker's live registry in one exposition, samples
        tagged ``worker="<i>"`` (the parent's own registry rides
        along as ``worker="pool"``)."""
        reg = get_registry()
        texts = {}
        with span("serve.pool_merge", workers=len(self.worker_info)):
            for i, info in sorted(self.worker_info.items()):
                try:
                    texts[str(i)] = self._scrape(info, "/metrics")
                except Exception as exc:
                    reg.counter(
                        "tpudas_serve_pool_worker_unreachable_total",
                        "pool control-plane scrapes that failed to "
                        "reach a worker",
                    ).inc()
                    log_event(
                        "serve_pool_worker_unreachable",
                        worker=i,
                        error=f"{type(exc).__name__}: "
                              f"{str(exc)[:200]}",
                    )
            texts["pool"] = reg.to_prometheus()
        return merge_prometheus(texts)

    def health(self) -> dict:
        """The aggregate liveness rollup: ``ok`` only when every
        worker process is alive AND its control plane answers."""
        workers = {}
        counts = {"ok": 0, "dead": 0, "unreachable": 0}
        for i, info in sorted(self.worker_info.items()):
            proc = self._procs[i] if i < len(self._procs) else None
            if proc is None or not proc.is_alive():
                status = "dead"
            else:
                try:
                    self._scrape(info, "/metrics")
                    status = "ok"
                except Exception:
                    status = "unreachable"
            counts[status] += 1
            workers[str(i)] = {
                "status": status,
                "pid": info.get("pid"),
                "control_port": info.get("control_port"),
            }
        overall = (
            "ok" if counts["ok"] == len(workers) and workers
            else "degraded"
        )
        return {
            "status": overall,
            "port": self.port,
            "workers": workers,
            "counts": counts,
        }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="N-process tpudas serve pool over one shared "
                    "read-only store (SO_REUSEPORT data plane + "
                    "merged control plane)"
    )
    ap.add_argument("folder",
                    help="processed output folder (or, with --fleet, "
                         "the fleet root)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--workers", type=int, default=_DEFAULT_WORKERS)
    ap.add_argument("--control-port", type=int, default=None,
                    help="pool /metrics + /healthz port "
                         "(default: port + 1)")
    ap.add_argument("--max-inflight", type=int, default=8,
                    help="per-worker admission gate size")
    ap.add_argument("--cache-tiles", type=int, default=256,
                    help="per-worker decoded-tile LRU capacity")
    ap.add_argument("--fleet", action="store_true",
                    help="serve a fleet root: every worker mounts "
                         "every <root>/<stream_id>/")
    ap.add_argument("--store-url", default=None,
                    help="serve a remote pyramid from this object "
                         "store; each worker hydrates its own "
                         "mirror + cache (stateless replicas); "
                         "replica:urlA,urlB,... serves through a "
                         "replicated store with mirror failover "
                         "(SERVING.md multi-region recipe)")
    ap.add_argument("--store-prefix", default="",
                    help="stream prefix inside the store")
    ap.add_argument("--cache-dir", default=None,
                    help="base cache directory (per-worker subdirs)")
    ap.add_argument("--cache-bytes", type=int, default=None,
                    help="per-worker read-through cache budget")
    ap.add_argument("--live-bridge", default=None,
                    help="producer LiveBridge address (host:port; "
                         "TPUDAS_LIVE_BRIDGE on the producer) — every "
                         "data worker subscribes so /live fans out "
                         "across the pool")
    args = ap.parse_args(argv)
    if args.store_url and args.fleet:
        ap.error("--store-url and --fleet are mutually exclusive")
    control_port = (
        args.port + 1 if args.control_port is None else
        args.control_port
    )
    pool = ServePool(
        args.folder, host=args.host, port=args.port,
        workers=args.workers, control_port=control_port,
        fleet=args.fleet, max_inflight=args.max_inflight,
        cache_tiles=args.cache_tiles, store_url=args.store_url,
        store_prefix=args.store_prefix, cache_dir=args.cache_dir,
        cache_bytes=args.cache_bytes, live_bridge=args.live_bridge,
    )
    with pool:
        print(
            f"tpudas.serve pool: {pool.workers} workers on "
            f"{pool.base_url} (control {pool.control_url}) over "
            f"{pool.folder}"
        )
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            print("shutting down pool")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
