"""Incremental multi-resolution tile pyramid over processed output.

The streaming drivers append decimated low-frequency output to a
directory round by round; this module maintains, beside that output
(and the stream carry), a pyramid of progressively coarser reductions
of the same stream so the read side can answer a window query at ANY
zoom by touching O(pixels) bytes instead of O(window) full-resolution
samples:

- level 0 is the processed output grid itself (one row per output
  sample, all channels);
- level ``k+1`` reduces each complete group of ``factor`` level-``k``
  samples to one sample, carrying three aggregates per group — mean
  (the display value), min and max (the envelope, so extremes survive
  decimation) — via the shared rolling kernels
  (:func:`tpudas.ops.rolling.rolling_reduce`).

Layout (all under ``<folder>/.tiles/``):

- ``manifest.json`` — the authoritative state: grid anchor/step,
  factor, tile length, channel coordinates, and per-level appended
  sample counts.  Written atomically (tmp + rename) AFTER the tiles it
  describes, double-buffered as ``manifest.json.prev`` — the same
  crash-only discipline as the stream carry (tpudas.proc.stream) and
  ``health.json`` (tpudas.obs.health).
- ``L<level>/<tile_index>.npy`` — COMPLETE fixed-length tiles
  (``tile_len`` rows x all channels) as raw ``.npy`` arrays (no zip
  container: a tile read/write is one header + one contiguous block,
  ~10x cheaper than ``.npz`` at this size, and the per-round append
  rides the stream's hot path).  Level 0 tiles are ``(rows, n_ch)``
  data; coarser tiles stack the three aggregates as ``(3, rows,
  n_ch)`` in :data:`AGGS` order.  A tile file is written exactly once,
  when it completes — full tiles are immutable.
- ``L<level>/<tile_index>.tpt`` — the same complete tiles under a
  compressed store (``codec=`` / ``TPUDAS_CODEC=``, ISSUE 11): one
  self-describing :mod:`tpudas.codec` blob per tile, crc embedded (no
  ``.crc`` sidecar).  Only COMPLETE tiles are encoded — ``tails.npy``
  and the manifest stay raw, they are the mutable per-round hot path.
  Reads accept both suffixes (codec-preferred), so a legacy raw store
  keeps serving untouched and a half-converted (mixed) store is
  consistent file by file.  Under a LOSSY codec incoming rows are
  first *conditioned* onto the codec's representable grid
  (:attr:`tpudas.codec.Codec.condition`), so every value on disk —
  tails included — obeys the codec's error bound and the incremental
  build stays byte-identical to an offline rebuild.
- ``tails.npy`` — every level's trailing PARTIAL tile in one
  self-describing file (header: ``[n_entries, (level, planes, rows,
  base_hi, base_lo) ...]``, then the row data), rewritten atomically
  once per append.
  This is the steady-state trick: appending to N pyramid levels costs
  ONE tail write plus the occasional completed tile, not N partial-
  tile rewrites — filesystem ops, not bytes, dominate a small append.

Write ordering per append: completed tiles, then ``tails.npy``, then
the manifest — so the manifest never references rows that are not
durably on disk.  Rows beyond the manifest's count (a crashed
append's surplus) are sliced off at read time; a partial-tile read
prefers the tile's FILE when one exists (a crashed append that
completed the tile before the manifest advanced — its prefix is
byte-identical because the reduction is deterministic) and falls back
to ``tails.npy`` otherwise.  During one append the cascade reads its
just-written source rows from a write-through cache, so a steady
append touches the disk only to write.

Data gaps in the output stream become NaN rows on the level-0 grid and
propagate to NaN coarse samples, so a served window is honest about
missing spans at every zoom.

Restart resumes the pyramid from the manifest; :func:`sync_pyramid`
(the realtime driver's per-round hook) appends exactly the output rows
newer than the pyramid head, making the incremental build byte-
identical to a one-shot rebuild from the same output files.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from tpudas.codec import (
    CodecError,
    TILE_BLOB_SUFFIX,
    decode_tile,
    encode_tile,
    get_codec,
    parse_codec_spec,
)
from tpudas.core.timeutils import to_datetime64
from tpudas.integrity.checksum import (
    count_fallback,
    count_unstamped,
    read_json_verified,
    rotate_prev,
    verify_file_checksum,
    write_json_checksummed,
    write_npy_checksummed,
)
from tpudas.obs.registry import get_registry
from tpudas.resilience.faults import fault_point
from tpudas.utils.atomicio import atomic_write_bytes
from tpudas.utils.logging import log_event

__all__ = [
    "TILE_DIRNAME",
    "MANIFEST_FILENAME",
    "MANIFEST_VERSION",
    "AGGS",
    "CorruptStoreError",
    "TileStore",
    "append_patches",
    "block_reduce",
    "rebuild_pyramid",
    "sync_pyramid",
]


class CorruptStoreError(RuntimeError):
    """The pyramid's on-disk state is internally inconsistent (e.g.
    the manifest implies partial rows neither the tails file nor a
    tile file can supply).  A SERVER-side condition — the HTTP layer
    maps it to 500, never to a client 400.  The pyramid is derived
    data: delete ``.tiles/`` (or re-run :func:`sync_pyramid`) to
    rebuild it byte-identically from the outputs."""

TILE_DIRNAME = ".tiles"
MANIFEST_FILENAME = "manifest.json"
TAILS_FILENAME = "tails.npy"
MANIFEST_VERSION = 1
AGGS = ("mean", "min", "max")

_DEFAULT_FACTOR = 4
_DEFAULT_TILE_LEN = 256
_STORE_DTYPE = np.float32


def _resolve_codec(codec) -> tuple:
    """``(codec_id, params)`` from a spec string, an already-split
    ``(id, params)`` pair, or None — every codec entry point funnels
    through here so an unknown id fails loudly at config time."""
    if isinstance(codec, tuple):
        cid, params = codec
        if cid is not None:
            get_codec(cid)  # unknown id -> CodecError now, not at read
        return cid, dict(params or {})
    return parse_codec_spec(codec)


def rebuild_pyramid(
    folder, engine=None, factor=None, tile_len=None, codec=None
) -> int:
    """The degradation ladder's last pyramid rung: delete ``.tiles/``
    and rebuild it from the output files via :func:`sync_pyramid` —
    byte-identical to the incremental build, because the reduction is
    deterministic.  The original ``factor``/``tile_len``/codec are
    recovered from whatever manifest rung still parses (the geometry
    must survive the rebuild, or the "byte-identical" claim breaks);
    env defaults apply only when nothing is recoverable.

    ``codec`` is also the offline **re-encode** entry point (ISSUE
    11): pass a codec spec (``"bitshuffle-deflate"``,
    ``"quantize-deflate:max_error=1e-3"``, or ``"raw"`` to strip
    compression) to rebuild the whole pyramid in that format; the
    default (None) preserves the store's recorded codec.  The
    manifest ``generation`` is bumped either way, so query-layer
    decoded-tile caches can never serve a pre-rebuild array.

    Returns the number of level-0 rows in the rebuilt pyramid."""
    import json as _json
    import shutil

    tiles_dir = os.path.join(str(folder), TILE_DIRNAME)
    # recovery always runs (not just for missing args): the
    # generation counter must survive the rebuild, or a held query
    # engine could key rebuilt tiles back into pre-rebuild cache slots
    generation = 0
    recovered_codec: tuple | None = None
    store = TileStore.open(folder)
    if store is not None:
        factor = factor or store.factor
        tile_len = tile_len or store.tile_len
        generation = store.generation
        recovered_codec = (store.codec, store.codec_params)
    else:
        # last resort: a raw (checksum-ignored) parse of either
        # manifest rung just for the geometry + codec fields
        base = os.path.join(tiles_dir, MANIFEST_FILENAME)
        for path in (base, base + ".prev"):
            try:
                with open(path) as fh:
                    raw = _json.load(fh)
                factor = factor or int(raw["factor"])
                tile_len = tile_len or int(raw["tile_len"])
                generation = int(raw.get("generation", 0))
                recovered_codec = (
                    raw.get("codec") or None,
                    dict(raw.get("codec_params") or {}),
                )
                break
            except (OSError, ValueError, KeyError, TypeError):
                continue
    if codec is None:
        codec = recovered_codec  # preserve the recorded format
    if os.path.isdir(tiles_dir):
        shutil.rmtree(tiles_dir, ignore_errors=True)
    get_registry().counter(
        "tpudas_serve_pyramid_rebuilds_total",
        "tile pyramids deleted and rebuilt from the output files "
        "(corrupt-store recovery)",
    ).inc()
    log_event("pyramid_rebuilt", folder=str(folder))
    # the rebuilt store is a NEW tile generation: even a content-
    # identical lossless rebuild bumps it (cheap — one cold refill of
    # the decoded-tile LRU), because a lossy or cross-codec rebuild
    # MUST invalidate every cached decoded array.  The bumped counter
    # goes into the FRESH manifest from its very first save — a
    # post-sync fixup would leave a window (or, after a crash mid-
    # rebuild, a permanent state) where re-encoded tiles still read
    # as the old generation and key into stale cache slots
    return sync_pyramid(
        folder, factor=factor, tile_len=tile_len, engine=engine,
        codec=codec, generation=int(generation) + 1,
    )


def block_reduce(x, factor: int, op: str, engine=None) -> np.ndarray:
    """Reduce complete groups of ``factor`` rows of ``x`` (rows x
    channels) to one row each — ``x`` must have ``g * factor`` rows.

    Equivalent to :func:`tpudas.ops.rolling.rolling_reduce` with a
    trailing window of ``factor`` sampled at the complete-window
    positions, and the device (``engine="jax"``) path goes through
    exactly that kernel.  The host default reduces the ``(g, factor,
    C)`` reshape directly in float64 — same groups, deterministic, and
    it sits on the realtime driver's per-round hot path so it must not
    pay for the stride-1 windows it would throw away.  NaN rows
    propagate to their group's output under every op (gap honesty).
    """
    x = np.asarray(x)
    if x.shape[0] % factor:
        raise ValueError(
            f"block_reduce needs complete groups: {x.shape[0]} rows "
            f"is not a multiple of factor {factor}"
        )
    if x.shape[0] == 0:
        return x.astype(np.float64)
    if engine not in (None, "numpy", "host"):
        from tpudas.ops.rolling import rolling_reduce

        full = np.asarray(
            rolling_reduce(x, factor, 1, op, axis=0, engine=engine)
        )
        return full[factor - 1 :: factor]
    g = x.shape[0] // factor
    grouped = x.astype(np.float64).reshape((g, factor) + x.shape[1:])
    if op == "mean":
        return grouped.mean(axis=1)
    if op == "sum":
        return grouped.sum(axis=1)
    if op == "max":
        return grouped.max(axis=1)
    if op == "min":
        return grouped.min(axis=1)
    raise ValueError(f"unknown block_reduce op {op!r}")


@dataclass
class TileStore:
    """The pyramid writer/reader for one output folder.

    Create with :meth:`create` (fresh) or :meth:`open` (resume from the
    manifest); the realtime driver goes through :func:`sync_pyramid`
    which does both.  All mutation happens in :meth:`append`; the
    manifest on disk is only advanced after every tile it references
    is durably in place.
    """

    folder: str
    factor: int = _DEFAULT_FACTOR
    tile_len: int = _DEFAULT_TILE_LEN
    engine: str | None = None  # reduction engine ("numpy" = host, default)
    # tile codec id (tpudas.codec registry; None = legacy raw .npy)
    # + its persisted parameters — both recorded in the manifest, so
    # the store that wrote a tile always knows how to read it back
    codec: str | None = None
    codec_params: dict = field(default_factory=dict)
    # bumped by rebuild_pyramid: lets the query engine's decoded-tile
    # LRU key out stale entries after a re-encode (same tile index,
    # different bytes)
    generation: int = 0
    t0_ns: int | None = None  # grid anchor (first level-0 sample time)
    step_ns: int | None = None  # level-0 grid step
    n_ch: int | None = None
    distance: np.ndarray | None = None
    levels: list = field(default_factory=list)  # appended samples per level
    # (mtime_ns, size) of the manifest last parsed — refresh() is a
    # stat when nothing changed, not a re-parse (the warm-query path)
    _manifest_stat: tuple | None = None
    # append-scoped write-through cache {(level, tile_idx): stored
    # array}: the cascade reads its just-written source rows from
    # memory; cleared at the start of every append
    _wcache: dict = field(default_factory=dict)
    # per-level trailing partial-tile rows, mirrored to the shared
    # tails.npy once per append.  ONE attribute holding ONE immutable
    # snapshot ({level: array}, {level: base_tile}) — None = not
    # loaded — so concurrent server threads racing a refresh always
    # read a fully-populated pair (attribute assignment is atomic;
    # a loaded-flag + two dicts is not).  base_tile records WHICH
    # tile each tail belongs to, so a crash-skewed (older-manifest,
    # newer-tails) pairing can never be misread as another tile's
    # rows.
    _tails_state: tuple | None = None

    # -- paths ---------------------------------------------------------
    @property
    def tiles_dir(self) -> str:
        return os.path.join(self.folder, TILE_DIRNAME)

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.tiles_dir, MANIFEST_FILENAME)

    @property
    def tails_path(self) -> str:
        return os.path.join(self.tiles_dir, TAILS_FILENAME)

    def tile_path(self, level: int, tile_idx: int) -> str:
        return os.path.join(
            self.tiles_dir, f"L{int(level)}", f"{int(tile_idx):08d}.npy"
        )

    def tile_blob_path(self, level: int, tile_idx: int) -> str:
        return os.path.join(
            self.tiles_dir,
            f"L{int(level)}",
            f"{int(tile_idx):08d}{TILE_BLOB_SUFFIX}",
        )

    def resolve_tile_path(self, level: int, tile_idx: int) -> str | None:
        """The on-disk file for one tile, whichever format it is in —
        the store's codec format preferred, the other accepted (a
        mixed raw+compressed store reads consistently file by file).
        None when neither exists."""
        blob = self.tile_blob_path(level, tile_idx)
        raw = self.tile_path(level, tile_idx)
        for path in (blob, raw) if self.codec else (raw, blob):
            if os.path.isfile(path):
                return path
        return None

    # -- lifecycle -----------------------------------------------------
    @classmethod
    def create(
        cls,
        folder,
        factor: int = _DEFAULT_FACTOR,
        tile_len: int = _DEFAULT_TILE_LEN,
        engine=None,
        codec=None,
    ) -> "TileStore":
        """A fresh, empty pyramid for ``folder`` (no manifest written
        until the first :meth:`append`).  ``codec`` is a
        :func:`tpudas.codec.parse_codec_spec` spec string (or
        ``(id, params)`` pair) selecting the compressed tile format;
        None/"raw" keeps legacy raw ``.npy`` tiles."""
        if int(factor) < 2:
            raise ValueError(f"pyramid factor must be >= 2, got {factor}")
        if int(tile_len) < int(factor):
            raise ValueError(
                f"tile_len {tile_len} must be >= factor {factor}"
            )
        codec_id, codec_params = _resolve_codec(codec)
        return cls(
            folder=str(folder),
            factor=int(factor),
            tile_len=int(tile_len),
            engine=engine,
            codec=codec_id,
            codec_params=codec_params,
        )

    @classmethod
    def open(cls, folder, engine=None) -> "TileStore | None":
        """Resume a pyramid from its manifest; None when ``folder`` has
        no (readable) manifest — the no-pyramid signal the query
        engine's full-resolution fallback keys off."""
        store = cls(folder=str(folder), engine=engine)
        if store._load_manifest():
            return store
        return None

    @classmethod
    def open_or_create(cls, folder, **kwargs) -> "TileStore":
        store = cls.open(folder, engine=kwargs.get("engine"))
        if store is not None:
            return store
        return cls.create(folder, **kwargs)

    def _load_manifest(self) -> bool:
        """Load the manifest (``.prev`` double-buffer fallback for a
        torn primary).  Returns True when a valid manifest was read;
        on failure the in-memory state is CLEARED — a store whose
        ``.tiles/`` was deleted out from under it (the documented
        corruption remedy) must read as empty, not keep serving a
        phantom pyramid or re-write a manifest over missing tiles."""
        base = self.manifest_path
        for path in (base, base + ".prev"):
            try:
                try:
                    st = os.stat(path)
                    stat_key = (st.st_mtime_ns, st.st_size)
                except OSError:
                    stat_key = None
                raw, status = read_json_verified(path, "manifest")
                if status == "mismatch":
                    raise ValueError("manifest checksum mismatch")
                if status == "unstamped":
                    count_unstamped("manifest")
                if raw.get("version") != MANIFEST_VERSION:
                    raise ValueError(
                        f"unknown pyramid manifest version "
                        f"{raw.get('version')!r}"
                    )
                self.factor = int(raw["factor"])
                self.tile_len = int(raw["tile_len"])
                self.t0_ns = int(raw["t0_ns"])
                self.step_ns = int(raw["step_ns"])
                self.n_ch = int(raw["n_ch"])
                self.distance = np.asarray(raw["distance"], dtype=np.float64)
                self.levels = [int(n) for n in raw["levels"]]
                # codec keys are absent on pre-ISSUE-11 manifests:
                # their absence IS the raw-store signal
                codec = raw.get("codec") or None
                if codec is not None:
                    get_codec(codec)  # unknown id = unreadable store
                self.codec = codec
                self.codec_params = dict(raw.get("codec_params") or {})
                self.generation = int(raw.get("generation", 0))
                # stat-gate future refreshes only off the PRIMARY (a
                # .prev fallback must re-check the primary next time)
                self._manifest_stat = stat_key if path == base else None
                # tails follow the manifest: reload lazily on demand
                self._tails_state = None
                return True
            except FileNotFoundError:
                continue
            except (OSError, ValueError, KeyError, TypeError,
                    CodecError) as exc:
                get_registry().counter(
                    "tpudas_serve_manifest_unreadable_total",
                    "pyramid manifests that failed to parse (fell back "
                    "to .prev or empty)",
                ).inc()
                count_fallback(
                    "manifest",
                    f"{type(exc).__name__}: {str(exc)[:120]}",
                    path,
                )
                log_event(
                    "pyramid_manifest_unreadable",
                    path=path,
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                )
                continue
        self.t0_ns = None
        self.step_ns = None
        self.n_ch = None
        self.distance = None
        self.levels = []
        self.codec = None
        self.codec_params = {}
        self.generation = 0
        self._manifest_stat = None
        self._tails_state = None
        return False

    def refresh(self) -> "TileStore":
        """Re-read the manifest (the server's view of a pyramid a
        writer is concurrently appending to).  Costs one ``stat`` when
        nothing changed — the warm-query hot path must not re-parse
        JSON per request."""
        if self._manifest_stat is not None:
            try:
                st = os.stat(self.manifest_path)
                if (st.st_mtime_ns, st.st_size) == self._manifest_stat:
                    return self
            except OSError:
                pass  # vanished mid-write: fall through to the loader
        self._load_manifest()
        return self

    def _save_manifest(self) -> None:
        payload = {
            "version": MANIFEST_VERSION,
            "factor": self.factor,
            "tile_len": self.tile_len,
            "t0_ns": int(self.t0_ns),
            "step_ns": int(self.step_ns),
            "n_ch": int(self.n_ch),
            "distance": [float(d) for d in self.distance],
            "levels": [int(n) for n in self.levels],
        }
        if self.codec is not None:
            # keys only present on compressed stores, so a raw store's
            # manifest is byte-identical to what pre-codec code wrote
            payload["codec"] = self.codec
            payload["codec_params"] = dict(self.codec_params)
        if self.generation:
            payload["generation"] = int(self.generation)
        path = self.manifest_path
        # rename-not-copy double buffer, same as health.json: the
        # outgoing good manifest survives as .prev for torn-read
        # readers; the write carries an embedded crc32 stamp
        rotate_prev(path)
        write_json_checksummed(path, payload)
        # our in-memory state IS this manifest: stat-gate so a writer
        # held across rounds never re-parses its own save
        try:
            st = os.stat(path)
            self._manifest_stat = (st.st_mtime_ns, st.st_size)
        except OSError:
            self._manifest_stat = None

    # -- geometry ------------------------------------------------------
    @property
    def n_levels(self) -> int:
        return len(self.levels)

    def level_step_ns(self, level: int) -> int:
        return int(self.step_ns) * int(self.factor) ** int(level)

    def n(self, level: int) -> int:
        return self.levels[level] if level < len(self.levels) else 0

    def time_of(self, level: int, i: int) -> int:
        """ns timestamp of level-``level`` sample ``i`` — the time of
        the FIRST level-0 sample in its group (leading-edge
        alignment)."""
        return int(self.t0_ns) + int(i) * self.level_step_ns(level)

    @property
    def head_ns(self) -> int | None:
        """Exclusive end of level-0 coverage (``None`` while empty)."""
        if self.t0_ns is None or not self.levels:
            return None
        return self.t0_ns + self.levels[0] * int(self.step_ns)

    # -- reading -------------------------------------------------------
    @staticmethod
    def _tile_dict(level: int, arr: np.ndarray, valid: int) -> dict:
        """{agg: (rows, n_ch)} view of one stored tile array.  Level 0
        serves its single data plane as every aggregate."""
        if level == 0:
            data = arr[:valid]
            return {agg: data for agg in AGGS}
        return {agg: arr[i, :valid] for i, agg in enumerate(AGGS)}

    # -- tails (the shared partial-tile file) --------------------------
    def _ensure_tails(self) -> tuple:
        """The current ``({level: rows}, {level: base_tile})``
        snapshot, loading it from disk at most once per manifest
        generation.  Callers hold the returned PAIR — never re-read
        the attribute mid-operation — so a concurrent refresh can
        only swap in a complete newer snapshot, never a half-built
        one."""
        state = self._tails_state
        if state is None:
            state = self._load_tails()
        return state

    def _load_tails(self) -> tuple:
        """Parse ``tails.npy`` (self-describing: ``[n_entries, (level,
        planes, rows, base_hi, base_lo)...]`` header, float32 row
        data) into one atomic (tails, bases) snapshot."""
        tails: dict = {}
        bases: dict = {}
        path = self.tails_path
        if os.path.isfile(path):
            fault_point("serve.tile_read", path=path)
            if verify_file_checksum(path, artifact="tails") == "mismatch":
                count_fallback("tails", "checksum mismatch", path)
                raise CorruptStoreError(
                    f"pyramid tails file {path!r} failed its crc32 "
                    f"check — delete {TILE_DIRNAME}/ (or run "
                    "tools/fsck.py) to rebuild"
                )
            try:
                flat = np.load(path)
                k = int(round(float(flat[0])))
                off = 1 + 5 * k
                n_ch = int(self.n_ch)
                for j in range(k):
                    level = int(round(float(flat[1 + 5 * j])))
                    planes = int(round(float(flat[2 + 5 * j])))
                    rows = int(round(float(flat[3 + 5 * j])))
                    # base tile index split into two sub-2^20 fields:
                    # each is exact in float32, together good to 2^40
                    # tiles — a single float32 silently rounds past
                    # 2^24 and would mis-tag the tail after ~decades
                    base = (
                        int(round(float(flat[4 + 5 * j]))) * (1 << 20)
                        + int(round(float(flat[5 + 5 * j])))
                    )
                    cnt = planes * rows * n_ch
                    arr = flat[off : off + cnt].reshape(
                        planes, rows, n_ch
                    )
                    off += cnt
                    tails[level] = arr[0] if level == 0 else arr
                    bases[level] = base
            except (ValueError, IndexError) as exc:
                # a torn/garbled tails file is SERVER-side corruption,
                # not a caller mistake
                count_fallback(
                    "tails", f"{type(exc).__name__}: {str(exc)[:120]}",
                    path,
                )
                raise CorruptStoreError(
                    f"unreadable pyramid tails file {path!r}: "
                    f"{type(exc).__name__}: {exc} — delete "
                    f"{TILE_DIRNAME}/ to rebuild"
                ) from exc
            get_registry().counter(
                "tpudas_serve_tile_loads_total",
                "pyramid tile files loaded from disk",
            ).inc()
        state = (tails, bases)
        self._tails_state = state  # single atomic publication
        return state

    def _save_tails(self) -> None:
        """One atomic write carrying EVERY level's partial tile — the
        append's fixed cost, independent of how many levels moved."""
        tails, bases = self._ensure_tails()
        entries, chunks = [], []
        for level in sorted(tails):
            arr = tails[level]
            if level == 0:
                planes, rows = 1, int(arr.shape[0])
            else:
                planes, rows = int(arr.shape[0]), int(arr.shape[1])
            if rows == 0:
                continue
            base = int(bases.get(level, 0))
            entries.append(
                (level, planes, rows, base >> 20, base & ((1 << 20) - 1))
            )
            chunks.append(np.asarray(arr, _STORE_DTYPE).reshape(-1))
        header = np.asarray(
            [len(entries)] + [v for e in entries for v in e],
            dtype=_STORE_DTYPE,
        )
        payload = (
            np.concatenate([header] + chunks) if chunks else header
        )
        os.makedirs(self.tiles_dir, exist_ok=True)
        write_npy_checksummed(self.tails_path, payload)

    def _tail_for(self, level: int, tile_idx: int, rows: int):
        """The tails entry for ``tile_idx`` of ``level`` when it
        exists, belongs to THAT tile, and carries at least ``rows``
        rows — else None.  The base-tile tag is what makes an
        older-manifest/newer-tails crash pairing safe: rows of a
        different tile can never be served as this one's."""
        tails, bases = self._ensure_tails()
        arr = tails.get(level)
        if arr is None or bases.get(level) != int(tile_idx):
            return None
        row_ax = 0 if level == 0 else 1
        if arr.shape[row_ax] < rows:
            return None
        return arr

    def _partial_rows(self, level: int, tile_idx: int, off: int):
        """The first ``off`` rows of the partial tile, in stored
        layout: from the in-memory/loaded tails when they cover it
        (the steady path — no stat, no read), else from the tile's
        FILE (a crashed append completed the tile before the manifest
        advanced — determinism makes its prefix our rows)."""
        row_ax = 0 if level == 0 else 1
        keep = (slice(None),) * row_ax + (slice(0, off),)
        arr = self._tail_for(level, tile_idx, off)
        if arr is not None:
            return arr[keep]
        path = self.resolve_tile_path(level, tile_idx)
        if path is not None:
            arr = self._read_tile_file(path)
            if arr.shape[row_ax] >= off:
                return arr[keep]
        raise CorruptStoreError(
            f"pyramid level {level} tile {tile_idx} holds fewer "
            f"partial rows than the manifest implies ({off}) — store "
            f"corrupt; delete {TILE_DIRNAME}/ to rebuild"
        )

    def _load_tile(self, level: int, tile_idx: int) -> dict:
        """One tile's aggregate arrays ``{agg: (rows, n_ch)}``, sliced
        to the manifest's sample count (a crashed append's surplus
        rows are invisible).  The head's partial tile comes from the
        tails file unless a crashed-future complete tile file covers
        it."""
        path = self.tile_path(level, tile_idx)
        n_level = self.n(level)
        valid = min(self.tile_len, n_level - tile_idx * self.tile_len)
        if valid <= 0:
            raise IndexError(
                f"tile L{level}/{tile_idx} is beyond the manifest head "
                f"({n_level} samples)"
            )
        if valid < self.tile_len:
            tail = self._tail_for(level, tile_idx, valid)
            if tail is not None:
                return self._tile_dict(level, tail, valid)
            # fall through: a crashed-future complete tile file covers
            # the partial index (its prefix is byte-identical)
        arr = self._read_tile_file(
            self.resolve_tile_path(level, tile_idx) or path
        )
        return self._tile_dict(level, arr, valid)

    def _read_tile_file(self, path: str) -> np.ndarray:
        """One tile file's array, whichever format it is in: a
        ``.tpt`` blob decodes through :mod:`tpudas.codec` (embedded
        crc verified), a raw ``.npy`` goes through the sidecar gate.
        A missing file surfaces as ``FileNotFoundError`` (absence is
        the caller's decision, same as the raw path always was)."""
        fault_point("serve.tile_read", path=path)
        if path.endswith(TILE_BLOB_SUFFIX):
            with open(path, "rb") as fh:
                blob = fh.read()
            try:
                arr = decode_tile(blob)
            except CodecError as exc:
                count_fallback(
                    "tile", f"{type(exc).__name__}: {str(exc)[:120]}",
                    path,
                )
                raise CorruptStoreError(
                    f"compressed pyramid tile {path!r} failed to "
                    f"decode ({exc}) — delete {TILE_DIRNAME}/ (or run "
                    "tools/fsck.py) to rebuild"
                ) from exc
        else:
            self._verify_tile(path)
            arr = np.load(path)
        get_registry().counter(
            "tpudas_serve_tile_loads_total",
            "pyramid tile files loaded from disk",
        ).inc()
        return arr

    @staticmethod
    def _verify_tile(path: str) -> None:
        """Checksum gate before trusting one tile file's bytes (an
        unstamped legacy tile is accepted — the audit re-stamps it)."""
        try:
            status = verify_file_checksum(path, artifact="tile")
        except FileNotFoundError:
            return  # absence surfaces as np.load's own error
        if status == "mismatch":
            count_fallback("tile", "checksum mismatch", path)
            raise CorruptStoreError(
                f"pyramid tile {path!r} failed its crc32 check — "
                f"delete {TILE_DIRNAME}/ (or run tools/fsck.py) to "
                "rebuild"
            )
        if status == "unstamped":
            count_unstamped("tile")

    def read(self, level, lo, hi, agg="mean", loader=None) -> np.ndarray:
        """Level-``level`` samples ``[lo, hi)`` of one aggregate as a
        ``(hi - lo, n_ch)`` array.  ``loader(level, tile_idx) -> {agg:
        array}`` overrides the disk tile read — the query engine
        injects its caching, request-coalescing loader here."""
        if agg not in AGGS:
            raise ValueError(f"unknown aggregate {agg!r}; known: {AGGS}")
        lo, hi = int(lo), int(hi)
        n_level = self.n(level)
        if lo < 0 or hi > n_level or lo > hi:
            raise IndexError(
                f"level {level} read [{lo}, {hi}) out of range "
                f"(have {n_level} samples)"
            )
        if hi == lo:
            return np.empty((0, int(self.n_ch)), dtype=_STORE_DTYPE)
        load = loader if loader is not None else self._load_tile
        tl = self.tile_len
        parts = []
        for t_idx in range(lo // tl, (hi - 1) // tl + 1):
            tile = load(level, t_idx)[agg]
            a = max(lo - t_idx * tl, 0)
            b = min(hi - t_idx * tl, tl)
            parts.append(tile[a:b])
        return np.concatenate(parts, axis=0)

    # -- appending -----------------------------------------------------
    def _write_tile(self, level: int, tile_idx: int, arr) -> None:
        """Write one COMPLETED tile in the store's format: a
        :mod:`tpudas.codec` blob (crc embedded) under a codec, the
        legacy checksummed raw ``.npy`` otherwise.  Either way the
        write is atomic and funnels through the ``fs.write_enospc``
        fault site, so ENOSPC shedding and the crash drill cover the
        compressed store identically."""
        if self.codec is not None:
            blob = encode_tile(arr, self.codec, **self.codec_params)
            atomic_write_bytes(self.tile_blob_path(level, tile_idx), blob)
            return
        write_npy_checksummed(self.tile_path(level, tile_idx), arr)

    def _condition_rows(self, arr: np.ndarray) -> np.ndarray:
        """Map rows onto the codec's representable set before they
        touch tails or tiles (lossy codecs only; identity otherwise).
        This is what keeps a lossy store deterministic: every stored
        value roundtrips the codec bit-exactly, so append chunking,
        crash replay, and offline rebuild all converge on the same
        bytes — and the error bound holds uniformly, tails included."""
        if self.codec is None:
            return arr
        codec = get_codec(self.codec)
        if codec.condition is None:
            return arr
        return np.ascontiguousarray(
            codec.condition(arr, **self.codec_params)
        )

    def _append_level(self, level: int, stacked: np.ndarray) -> None:
        """Append rows to one level — ``stacked`` is ``(rows, n_ch)``
        at level 0, ``(3, rows, n_ch)`` (AGGS order) above.  COMPLETED
        tiles are written to their own files (immutable, once); the
        trailing partial rows stay in the tails snapshot and hit disk via
        the shared single-file :meth:`_save_tails` at the end of the
        append.  Everything written lands in the append-scoped
        write-through cache so the cascade reduces from memory."""
        row_ax = 0 if level == 0 else 1
        total = stacked.shape[row_ax]
        if total == 0:
            return
        tails, bases = self._ensure_tails()
        n = self.n(level)
        tl = self.tile_len
        off = n % tl
        base = n // tl
        if off:
            combined = np.concatenate(
                [self._partial_rows(level, base, off), stacked],
                axis=row_ax,
            )
        else:
            combined = stacked
        rows_comb = combined.shape[row_ax]
        n_full = rows_comb // tl
        if n_full:
            os.makedirs(
                os.path.join(self.tiles_dir, f"L{int(level)}"),
                exist_ok=True,
            )
        for j in range(n_full):
            sl = (slice(None),) * row_ax + (slice(j * tl, (j + 1) * tl),)
            tile = np.ascontiguousarray(combined[sl])
            self._write_tile(level, base + j, tile)
            self._wcache[(level, base + j)] = tile
        sl = (slice(None),) * row_ax + (slice(n_full * tl, rows_comb),)
        rem = np.ascontiguousarray(combined[sl])
        # single-writer mutation of the published snapshot dicts (the
        # driver is the only appender; server readers are other
        # processes, or read-only threads that took their own snapshot)
        tails[level] = rem
        bases[level] = base + n_full
        if rem.shape[row_ax]:
            self._wcache[(level, base + n_full)] = rem

    def append(self, times, data) -> int:
        """Append output rows to the pyramid and cascade the coarser
        levels.  ``times`` are datetime64 (ascending, on the output
        grid); ``data`` is (rows, n_ch).  Rows at or before the current
        head are dropped (idempotent re-append); an on-grid hole ahead
        of the head is filled with NaN rows.  Returns the number of
        grid rows the pyramid advanced by (fills included).
        """
        times = np.asarray(times).astype("datetime64[ns]")
        data = np.asarray(data, dtype=_STORE_DTYPE)
        if data.ndim != 2 or data.shape[0] != times.shape[0]:
            raise ValueError(
                f"append needs (rows, n_ch) data matching times; got "
                f"data {data.shape} for {times.shape[0]} times"
            )
        if times.size == 0:
            return 0
        t_ns = times.astype(np.int64)
        if self.t0_ns is None:
            if times.size < 2:
                raise ValueError(
                    "cannot infer the grid step from a single-row first "
                    "append; append at least two rows"
                )
            self.t0_ns = int(t_ns[0])
            self.step_ns = int(np.median(np.diff(t_ns)))
            if self.step_ns <= 0:
                raise ValueError("times must be strictly increasing")
            self.n_ch = int(data.shape[1])
            self.distance = np.arange(self.n_ch, dtype=np.float64)
            self.levels = [0]
        if data.shape[1] != self.n_ch:
            raise ValueError(
                f"channel count changed: pyramid has {self.n_ch}, "
                f"append got {data.shape[1]}"
            )
        step = int(self.step_ns)
        rel = t_ns - int(self.t0_ns)
        idx = np.round(rel / step).astype(np.int64)
        if np.any(np.abs(rel - idx * step) > 0.01 * step):
            raise ValueError(
                "append times are not on the pyramid grid "
                f"(anchor {self.t0_ns} ns, step {step} ns)"
            )
        if np.any(np.diff(idx) <= 0):
            raise ValueError("append times must be strictly increasing")
        n0 = self.levels[0]
        keep = idx >= n0
        if not np.any(keep):
            return 0
        idx = idx[keep]
        data = data[keep]
        # place rows on the contiguous grid [n0, last+1); holes -> NaN
        last = int(idx[-1])
        block = np.full((last + 1 - n0, self.n_ch), np.nan,
                        dtype=_STORE_DTYPE)
        block[idx - n0] = data
        block = self._condition_rows(block)
        self._wcache.clear()
        self._append_level(0, block)
        self.levels[0] = last + 1
        self._cascade()
        # durability order: completed tiles are already down; now the
        # tails, then the manifest that references them
        self._save_tails()
        self._wcache.clear()
        self._save_manifest()
        appended = int(block.shape[0])
        get_registry().counter(
            "tpudas_serve_pyramid_appended_samples_total",
            "level-0 grid rows appended to the tile pyramid "
            "(NaN gap fills included)",
        ).inc(appended)
        return appended

    def set_distance(self, distance) -> None:
        """Record the channel (distance) coordinates — called by
        :func:`sync_pyramid` from the first output patch so served
        windows carry real distances, not channel indices."""
        d = np.asarray(distance, dtype=np.float64)
        if self.n_ch is not None and d.shape[0] != self.n_ch:
            raise ValueError(
                f"distance coords ({d.shape[0]}) != channels "
                f"({self.n_ch})"
            )
        self.distance = d

    def _cascade_loader(self, level: int, tile_idx: int) -> dict:
        """Tile loader for the cascade: the append's write-through
        cache first (the just-written source rows), disk only for the
        occasional pre-existing backlog tile."""
        cached = self._wcache.get((level, tile_idx))
        if cached is not None:
            valid = min(
                self.tile_len, self.n(level) - tile_idx * self.tile_len
            )
            return self._tile_dict(level, cached, valid)
        return self._load_tile(level, tile_idx)

    def _cascade(self) -> None:
        """Propagate complete groups of ``factor`` finer samples into
        each coarser level until no level has a complete new group."""
        f = int(self.factor)
        lvl = 0
        while True:
            n_src = self.n(lvl)
            n_dst = self.n(lvl + 1)
            g = n_src // f - n_dst
            if g <= 0:
                break
            lo, hi = n_dst * f, (n_dst + g) * f
            if lvl == 0:
                base = self.read(0, lo, hi, loader=self._cascade_loader)
                srcs = {agg: base for agg in AGGS}
            else:
                srcs = {
                    agg: self.read(
                        lvl, lo, hi, agg=agg, loader=self._cascade_loader
                    )
                    for agg in AGGS
                }
            reduced = np.stack(
                [
                    block_reduce(srcs[agg], f, agg, self.engine).astype(
                        _STORE_DTYPE
                    )
                    for agg in AGGS
                ],
                axis=0,
            )
            # coarse rows obey the codec's representable set too, so
            # their later tile encode is exact and chunk-independent
            reduced = self._condition_rows(reduced)
            self._append_level(lvl + 1, reduced)
            if lvl + 1 < len(self.levels):
                self.levels[lvl + 1] = n_dst + g
            else:
                self.levels.append(n_dst + g)
            lvl += 1


def sync_pyramid(
    folder,
    factor: int | None = None,
    tile_len: int | None = None,
    engine=None,
    since=None,
    codec=None,
    generation: int = 0,
) -> int:
    """Bring ``folder``'s tile pyramid up to date with its output
    files; returns the number of level-0 rows appended.

    The realtime driver's per-round hook (and the offline rebuild
    oracle): opens/creates the store from the manifest, reads ONLY the
    output rows newer than the pyramid head through the directory
    spool's pushed-down time selection, and appends them group by
    contiguous group.  ``since`` anchors a FRESH pyramid at a later
    start (outputs older than it stay full-resolution-only — the
    query engine's file fallback covers them).

    ``factor`` / ``tile_len`` / ``codec`` only shape a FRESH pyramid
    (an existing manifest wins); their defaults come from
    ``TPUDAS_PYRAMID_FACTOR`` / ``TPUDAS_PYRAMID_TILE_LEN`` /
    ``TPUDAS_CODEC`` (a codec spec string, e.g.
    ``bitshuffle-deflate`` or ``quantize-deflate:max_error=1e-3``) so
    an operator can tune tile granularity and compression without
    touching driver code.  Re-encoding an EXISTING store is
    :func:`rebuild_pyramid`'s job — which passes ``generation`` (the
    bumped cache-invalidation counter) through to the fresh store so
    its first manifest already carries it.
    """
    from tpudas.io.spool import spool as make_spool

    if factor is None:
        factor = int(
            os.environ.get("TPUDAS_PYRAMID_FACTOR", _DEFAULT_FACTOR)
        )
    if tile_len is None:
        tile_len = int(
            os.environ.get("TPUDAS_PYRAMID_TILE_LEN", _DEFAULT_TILE_LEN)
        )
    if codec is None:
        codec = os.environ.get("TPUDAS_CODEC")
    store = TileStore.open(folder, engine=engine)
    if store is None:
        store = TileStore.create(
            folder, factor=factor, tile_len=tile_len, engine=engine,
            codec=codec,
        )
        # non-zero only on the rebuild path: the fresh store's very
        # first manifest save must already carry the new generation
        store.generation = int(generation)
    head = store.head_ns
    lo = head
    if lo is None and since is not None:
        lo = int(to_datetime64(since).astype("datetime64[ns]").astype(np.int64))
    sp = make_spool(str(folder)).update()
    if lo is not None:
        sp = sp.select(time=(np.datetime64(int(lo), "ns"), None))
    if len(sp) == 0:
        return 0
    merged = sp.chunk(time=None)
    appended = 0
    for patch in merged:
        d = patch.host_data()
        ax = patch.axis_of("time")
        if ax != 0:
            d = np.moveaxis(d, ax, 0)
        times = np.asarray(patch.coords["time"]).astype("datetime64[ns]")
        t_ns = times.astype(np.int64)
        if lo is not None:
            m = t_ns >= int(lo)
            times, d = times[m], d[m]
        if times.size == 0:
            continue
        appended += _append_patch(store, times, d, patch)
    return appended


def _append_patch(store: TileStore, times, data, patch) -> int:
    """Append time-major rows plus (on the pyramid's first rows) the
    real distance coordinates from the source patch."""
    first_append = store.t0_ns is None
    appended = store.append(times, data)
    if first_append and store.t0_ns is not None:
        dist = patch.coords.get("distance")
        if dist is not None and len(dist) == store.n_ch:
            store.set_distance(dist)
            store._save_manifest()
    return appended


def append_patches(folder, patches, engine=None, store=None) -> tuple:
    """The realtime driver's FAST per-round path: append this round's
    freshly emitted output patches straight from memory — no index
    rescan, no re-read of files the process just wrote.  Returns
    ``(rows_appended, store_or_None)``; the caller passes the store
    back next round so a steady round costs one manifest ``stat``
    instead of a re-open (``None`` after any fallback — re-resolve
    from disk, the carry discipline).

    Correctness guard: the in-memory rows are used only when they are
    CONTIGUOUS with the pyramid head (overlap is fine — re-emitted
    rewind rows are dropped idempotently).  A fresh folder (no
    manifest yet) or a pyramid that fell behind the outputs (a crash
    between the output writes and the append) falls back to
    :func:`sync_pyramid`, which backfills from the files — so every
    path converges to the same byte-identical pyramid.
    """
    patches = [p for p in patches if p is not None]
    if store is not None:
        store.refresh()
    else:
        store = TileStore.open(folder, engine=engine)
    if store is None or store.head_ns is None or not patches:
        # no pyramid yet (anchor at the EARLIEST output, which may
        # predate this round) or nothing captured: authoritative sync
        return sync_pyramid(folder, engine=engine), None
    head = store.head_ns
    blocks = []
    for p in sorted(patches, key=lambda q: q.attrs["time_min"]):
        d = p.host_data()
        ax = p.axis_of("time")
        if ax != 0:
            d = np.moveaxis(d, ax, 0)
        t = np.asarray(p.coords["time"]).astype("datetime64[ns]")
        if t.size:
            blocks.append((t, d, p))
    if not blocks:
        return 0, store
    new_blocks = [
        b for b in blocks if int(b[0][-1].astype(np.int64)) >= head
    ]
    if not new_blocks:
        return 0, store  # pure re-emission (rewind overlap): nothing new
    lo_ns = int(new_blocks[0][0][0].astype(np.int64))
    if lo_ns > head:
        # rows missing between the pyramid head and this round's
        # capture (crashed append, listener gap): catch up from disk
        return sync_pyramid(folder, engine=engine), None
    # ONE append for the whole round: the cascade and the manifest
    # rename dance are paid once, not once per emitted patch (filesystem
    # ops dominate the steady-state append cost).  append() places the
    # concatenated rows on the grid itself, NaN-filling any interior
    # gap between blocks.
    times = np.concatenate([t for t, _, _ in new_blocks])
    data = np.concatenate([d for _, d, _ in new_blocks], axis=0)
    return _append_patch(store, times, data, new_blocks[0][2]), store
