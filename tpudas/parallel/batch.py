"""Data parallelism over independent patches.

The rolling-mean paths process spool patches independently
(rolling_mean_dascore.ipynb:147 is a serial for-loop; the *_edge
variant is per-new-file). TPU-native: stack patches into a leading
batch axis and shard it over the mesh — pure data parallelism, no
collectives."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudas.ops.rolling import _reduce_window_kernel

__all__ = ["batched_rolling_mean"]


def batched_rolling_mean(mesh, batch, w: int, s: int, batch_axis="ch"):
    """Rolling mean over a (B, T, C) stack of windows/patches, batch
    axis sharded over the mesh's ``batch_axis``.

    Uses the same reduce_window kernel (and NaN warm-up semantics) as
    the single-patch path, vmapped over the batch.
    """
    arr = jnp.asarray(batch, jnp.float32)
    sharding = NamedSharding(mesh, P(batch_axis, None, None))
    arr = jax.device_put(arr, sharding)
    fn = jax.vmap(
        functools.partial(_reduce_window_kernel, w=int(w), s=int(s), op="mean")
    )
    return jax.jit(fn, out_shardings=sharding)(arr)
