"""Data parallelism over independent patches.

The rolling-mean paths process spool patches independently
(rolling_mean_dascore.ipynb:147 is a serial for-loop; the *_edge
variant is per-new-file). TPU-native: stack patches into a leading
batch axis and shard it over the mesh — pure data parallelism, no
collectives."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudas.ops.rolling import _reduce_window_kernel

__all__ = ["batched_rolling_mean", "batched_cascade_decimate"]


def batched_rolling_mean(mesh, batch, w: int, s: int, batch_axis="ch"):
    """Rolling mean over a (B, T, C) stack of windows/patches, batch
    axis sharded over the mesh's ``batch_axis``.

    Uses the same reduce_window kernel (and NaN warm-up semantics) as
    the single-patch path, vmapped over the batch.
    """
    arr = jnp.asarray(batch, jnp.float32)
    sharding = NamedSharding(mesh, P(batch_axis, None, None))
    arr = jax.device_put(arr, sharding)
    fn = jax.vmap(
        functools.partial(_reduce_window_kernel, w=int(w), s=int(s), op="mean")
    )
    return jax.jit(fn, out_shardings=sharding)(arr)


@functools.lru_cache(maxsize=64)
def _build_batched_cascade_fn(
    plan, n_out, engine, mesh, batch_axis, ch_axis, quantized, knobs=()
):
    from tpudas.parallel.compat import shard_map

    from tpudas.ops.fir import (
        _apply_cascade_stages,
        _blocked_taps,
        _pallas_interpret,
    )

    blocked = _blocked_taps(plan)
    use_pallas = engine == "pallas"
    interpret = _pallas_interpret() if use_pallas else False
    spec = P(batch_axis, None, ch_axis)

    def one(x, scale=None):
        return _apply_cascade_stages(
            x, blocked, n_out, use_pallas, interpret, qscale=scale
        )

    if quantized:
        def body(stack, scale):
            return jax.vmap(lambda x: one(x, scale))(stack)

        in_specs = (spec, P())
    else:
        def body(stack):
            return jax.vmap(one)(stack)

        in_specs = (spec,)

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=spec,
            check_vma=False,
        )
    )


def batched_cascade_decimate(
    mesh, stack, plan, phase, n_out, engine="auto",
    batch_axis="time", ch_axis="ch", qscale=None,
):
    """Window-level DATA parallelism for the LF pipeline: a (W, T, C)
    stack of same-shape overlap-save windows, batch axis sharded over
    ``batch_axis`` (channels optionally over ``ch_axis`` too) — the
    BASELINE "spool chunks pmapped across a TPU mesh" configuration.
    Windows are independent, so there are zero collectives; each
    device runs the full cascade (vmapped over its local windows).

    Every window is decimated with the SAME (plan, phase, n_out) —
    the steady-state overlap-save schedule, where all interior windows
    share one shape.  Result equals stacking per-window
    :func:`tpudas.ops.fir.cascade_decimate` outputs.  ``qscale``
    accepts a raw int16 stack (one shared quantization scale).
    """
    from tpudas.ops.fir import (
        _check_quantized,
        resolve_cascade_engine,
        shift_to_phase,
    )

    engine = resolve_cascade_engine(engine)
    stack = jnp.asarray(stack)
    if qscale is not None:
        _check_quantized(stack, qscale)
    elif stack.dtype != jnp.float32:
        stack = stack.astype(jnp.float32)
    W, T, C = stack.shape
    stack = shift_to_phase(stack, phase, plan.delay, axis=1)
    nb = mesh.shape[batch_axis]
    # a mesh without the channel axis (e.g. a custom 1-axis DP mesh)
    # simply leaves channels unsharded
    if ch_axis not in mesh.shape:
        ch_axis = None
    nc = mesh.shape[ch_axis] if ch_axis else 1
    pad_w = -W % nb
    pad_c = -C % nc
    if pad_w or pad_c:
        stack = jnp.pad(stack, ((0, pad_w), (0, 0), (0, pad_c)))
    from tpudas.ops.fir import knob_fingerprint

    fn = _build_batched_cascade_fn(
        plan, int(n_out), engine, mesh, batch_axis, ch_axis,
        qscale is not None, knobs=knob_fingerprint(),
    )
    if qscale is not None:
        out = fn(stack, jnp.float32(qscale))
    else:
        out = fn(stack)
    return out[:W, :, :C] if pad_w or pad_c else out
