"""Sharded end-to-end window pipeline: filter + decimate over a mesh.

The multi-device form of the engine's fused window kernel
(tpudas.proc.lfproc): a resident (T, C) super-block is laid out over a
(time, ch) mesh; each device filters its time shard plus exchanged
halos locally (FFT overlap-save — circular artifacts fall inside the
trimmed halo), then decimates its interior by strided subsampling.
Channel direction needs no communication at all; time direction costs
one neighbor ppermute of ``halo`` rows per step.

Alignment requirements (checked): T divisible by time-shards, local
block divisible by the decimation ratio, C divisible by channel shards.
The streaming host path (LFProc) has no such constraints; this path is
for resident super-batches on a slice (BASELINE.json configs 4-5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from tpudas.ops.fftlen import next_tpu_fft_len
from tpudas.ops.filter import fft_lowpass_response
from tpudas.parallel.halo import exchange_halo_time

__all__ = ["sharded_lowpass_decimate"]


def _local_filter_decimate(padded, d_sec, corner, order, halo, t_local, ratio):
    """Filter a halo-padded local block, trim, stride-decimate."""
    nfft = next_tpu_fft_len(int(padded.shape[0]))
    spec = jnp.fft.rfft(padded, n=nfft, axis=0)
    resp = fft_lowpass_response(nfft, d_sec, corner, order)
    filt = jnp.fft.irfft(spec * resp[:, None], n=nfft, axis=0)
    interior = jax.lax.slice_in_dim(filt, halo, halo + t_local, axis=0)
    return interior[::ratio].astype(padded.dtype)


def sharded_lowpass_decimate(
    mesh, data, d_sec, corner, ratio, halo, order=4,
    time_axis="time", ch_axis="ch",
):
    """Run the fused low-pass + decimate over a (time, ch) mesh.

    data: (T, C) float32 (host or device). Returns (T // ratio, C) with
    the same global result as the single-device kernel up to halo
    truncation (callers discard ``halo`` input samples at each stream
    end, as the engine's edge buffer already does).
    """
    T, C = data.shape
    nt = mesh.shape[time_axis]
    nc = mesh.shape[ch_axis]
    if T % nt != 0:
        raise ValueError(f"T={T} not divisible by time shards {nt}")
    t_local = T // nt
    if t_local % ratio != 0:
        raise ValueError(
            f"local block {t_local} not divisible by decimation ratio {ratio}"
        )
    if C % nc != 0:
        raise ValueError(f"C={C} not divisible by channel shards {nc}")
    if halo >= t_local:
        raise ValueError(f"halo {halo} must be < local block {t_local}")

    spec_2d = P(time_axis, ch_axis)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec_2d,),
        out_specs=spec_2d,
        check_vma=False,
    )
    def step(block):
        padded = exchange_halo_time(
            block, halo, axis_name=time_axis, n_shards=nt
        )
        return _local_filter_decimate(
            padded,
            jnp.float32(d_sec),
            jnp.float32(corner),
            order,
            halo,
            t_local,
            ratio,
        )

    arr = jax.device_put(
        jnp.asarray(data, jnp.float32), NamedSharding(mesh, spec_2d)
    )
    return jax.jit(step)(arr)
