"""Sharded end-to-end window pipeline: filter + decimate over a mesh.

The multi-device form of the engine's fused window kernel
(tpudas.proc.lfproc): a resident (T, C) super-block is laid out over a
(time, ch) mesh; each device filters its time shard plus exchanged
halos locally (FFT overlap-save — circular artifacts fall inside the
trimmed halo), then decimates its interior by strided subsampling.
Channel direction needs no communication at all; time direction costs
one neighbor ppermute of ``halo`` rows per step.

Alignment requirements (checked): T divisible by time-shards, local
block divisible by the decimation ratio, C divisible by channel shards.
The streaming host path (LFProc) has no such constraints; this path is
for resident super-batches on a slice (BASELINE.json configs 4-5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudas.parallel.compat import shard_map

from tpudas.ops.fftlen import next_tpu_fft_len
from tpudas.ops.filter import fft_lowpass_response
from tpudas.parallel.halo import exchange_halo_time

__all__ = [
    "sharded_lowpass_decimate",
    "sharded_cascade_decimate",
    "sharded_cascade_layout",
]


def _local_filter_decimate(padded, d_sec, corner, order, halo, t_local, ratio):
    """Filter a halo-padded local block, trim, stride-decimate."""
    nfft = next_tpu_fft_len(int(padded.shape[0]))
    spec = jnp.fft.rfft(padded, n=nfft, axis=0)
    resp = fft_lowpass_response(nfft, d_sec, corner, order)
    filt = jnp.fft.irfft(spec * resp[:, None], n=nfft, axis=0)
    interior = jax.lax.slice_in_dim(filt, halo, halo + t_local, axis=0)
    return interior[::ratio].astype(padded.dtype)


def sharded_lowpass_decimate(
    mesh, data, d_sec, corner, ratio, halo, order=4,
    time_axis="time", ch_axis="ch",
):
    """Run the fused low-pass + decimate over a (time, ch) mesh.

    data: (T, C) float32 (host or device). Returns (T // ratio, C) with
    the same global result as the single-device kernel up to halo
    truncation (callers discard ``halo`` input samples at each stream
    end, as the engine's edge buffer already does).
    """
    T, C = data.shape
    nt = mesh.shape[time_axis]
    nc = mesh.shape[ch_axis]
    if T % nt != 0:
        raise ValueError(f"T={T} not divisible by time shards {nt}")
    t_local = T // nt
    if t_local % ratio != 0:
        raise ValueError(
            f"local block {t_local} not divisible by decimation ratio {ratio}"
        )
    if C % nc != 0:
        raise ValueError(f"C={C} not divisible by channel shards {nc}")
    if halo >= t_local:
        raise ValueError(f"halo {halo} must be < local block {t_local}")

    spec_2d = P(time_axis, ch_axis)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec_2d,),
        out_specs=spec_2d,
        check_vma=False,
    )
    def step(block):
        padded = exchange_halo_time(
            block, halo, axis_name=time_axis, n_shards=nt
        )
        return _local_filter_decimate(
            padded,
            jnp.float32(d_sec),
            jnp.float32(corner),
            order,
            halo,
            t_local,
            ratio,
        )

    arr = jax.device_put(
        jnp.asarray(data, jnp.float32), NamedSharding(mesh, spec_2d)
    )
    return jax.jit(step)(arr)


# ---------------------------------------------------------------------------
# time + channel sharded cascade (the product engine's mesh fast path)


@functools.lru_cache(maxsize=64)
def _build_sharded_cascade_fn(
    plan, n_loc, halo, engine, mesh, time_axis, ch_axis, quantized=False,
    knobs=(),
):
    """jit-compiled shard_map cascade: (nt*t_local, C) -> (nt*n_loc, C).

    Each time-shard receives its neighbors' halo rows over the ICI ring
    (``exchange_halo_time``), drops the unused left halo, and runs the
    causal cascade on its local block — valid because the cascade is
    shift-invariant under multiples of the composite ratio, and
    ``t_local = n_loc * ratio``. Channels split over ``ch_axis`` with
    no communication at all.
    """
    import jax

    from tpudas.ops.fir import (
        _apply_cascade_stages,
        _blocked_taps,
        _pallas_interpret,
    )

    nt = mesh.shape[time_axis]
    blocked = _blocked_taps(plan)
    use_pallas = engine == "pallas"
    interpret = _pallas_interpret() if use_pallas else False

    in_specs = (
        (P(time_axis, ch_axis), P())
        if quantized
        else (P(time_axis, ch_axis),)
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(time_axis, ch_axis),
        check_vma=False,
    )
    def step(block, *maybe_scale):
        # causal consumer: only the RIGHT (look-ahead) halo is needed,
        # so the exchange is one-sided — half the ICI traffic (and a
        # quantized int16 window keeps its halved payload across the
        # ring too: dequantization happens inside the first stage)
        padded = exchange_halo_time(
            block, halo, axis_name=time_axis, n_shards=nt, left=False
        )
        return _apply_cascade_stages(
            padded, blocked, n_loc, use_pallas, interpret,
            qscale=maybe_scale[0] if quantized else None,
        )

    return jax.jit(step)


def sharded_cascade_layout(mesh, plan, phase, n_out, T,
                           time_axis="time", n_ch_local=1, engine="auto"):
    """(n_loc, t_local, halo) of the time-sharded cascade layout for a
    T-row input — or ``None`` when it does not fit (a shard's halo
    would exceed its local block: too many time shards for this
    window/filter combination). Shared by the executor below and by
    callers that need to predict per-device shapes (e.g. LFProc's
    engine observability, which must see the LOCAL output count the
    Pallas threshold sees).

    ``n_ch_local``/``engine`` size the halo from the same chain layout
    the shard body will trace (Pallas stages consume grid-rounded
    inputs): a halo sized that way keeps every stage pad-free inside
    the shard. The defaults give the plain ``(k+B)*R`` sizing.
    """
    from tpudas.ops.fir import chain_layout

    nt = mesh.shape[time_axis]
    ratio = int(plan.ratio)
    n_out = int(n_out)
    if n_out < 1 or nt < 1:
        return None
    # rows of the pre-shifted stream (phase < delay adds left padding)
    T_shift = int(T) - (int(phase) - plan.delay)
    # the shard grid must cover ALL real input rows, not just
    # n_out*ratio of them: the last shard has no right neighbor, so any
    # data past the grid would be replaced by boundary zeros inside the
    # tail outputs' filter support
    n_loc = max(-(-n_out // nt), -(-T_shift // (ratio * nt)))
    t_local = n_loc * ratio
    _, rows_local = chain_layout(plan, n_loc, int(n_ch_local), engine)
    halo = rows_local - t_local
    if halo < 0 or halo > t_local:
        return None
    return n_loc, t_local, halo


def sharded_cascade_decimate(
    mesh, x, plan, phase, n_out, engine="auto",
    time_axis="time", ch_axis="ch", qscale=None,
):
    """Mesh-parallel :func:`tpudas.ops.fir.cascade_decimate`: the time
    axis is sharded over ``time_axis`` (one-sided halo exchange over
    ICI neighbors, sized from the cascade's exact input need) and
    channels over ``ch_axis`` (zero-comm).

    Bit-equal to the single-device cascade for the same (plan, phase,
    n_out): out-of-data rows are zero in both layouts and each output's
    reduction reads the same rows in the same order. Returns ``None``
    when the layout does not fit (see :func:`sharded_cascade_layout`);
    the caller then falls back to channel-only sharding.
    """
    import jax.numpy as jnp

    from tpudas.ops.fir import (
        _check_quantized,
        resolve_cascade_engine,
        shift_to_phase,
    )

    nt = mesh.shape[time_axis]
    nc = mesh.shape[ch_axis]
    n_ch_local = -(-int(np.shape(x)[1]) // nc)
    layout = sharded_cascade_layout(
        mesh, plan, phase, int(n_out), int(np.shape(x)[0]), time_axis,
        n_ch_local=n_ch_local, engine=engine,
    )
    if layout is None:
        return None
    n_loc, t_local, halo = layout
    n_out = int(n_out)
    engine = resolve_cascade_engine(engine)
    if qscale is not None:
        x = jnp.asarray(x)  # raw int16: dequantized inside stage 0
        _check_quantized(x, qscale)
    else:
        x = jnp.asarray(x, jnp.float32)
    C = int(x.shape[1])
    x2 = shift_to_phase(x, phase, plan.delay)
    T_target = nt * t_local
    pad_t = T_target - int(x2.shape[0])
    if pad_t > 0:
        x2 = jnp.pad(x2, ((0, pad_t), (0, 0)))
    pad_c = -C % nc
    if pad_c:
        x2 = jnp.pad(x2, ((0, 0), (0, pad_c)))
    from tpudas.ops.fir import knob_fingerprint

    fn = _build_sharded_cascade_fn(
        plan, n_loc, halo, engine, mesh, time_axis, ch_axis,
        quantized=qscale is not None, knobs=knob_fingerprint(),
    )
    if qscale is not None:
        out = fn(x2, jnp.float32(qscale))
    else:
        out = fn(x2)
    return out[:n_out, :C]
