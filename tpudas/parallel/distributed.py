"""Multi-host (DCN) initialization hooks.

Within a slice, collectives ride ICI and need no setup beyond the mesh.
Across hosts (e.g. a v5e-64 spanning multiple workers — BASELINE.json
config 5), JAX needs ``jax.distributed.initialize`` before first use;
these wrappers gate that so single-host usage (and CPU test meshes) is
untouched. The filesystem remains the durable inter-round channel, as
in the reference's crash-only design (lf_das.py:214-217)."""

from __future__ import annotations

import os

import jax

__all__ = ["initialize_multihost", "is_distributed", "global_mesh_devices"]

_initialized = False


def initialize_multihost(
    coordinator_address=None, num_processes=None, process_id=None
):
    """Idempotent ``jax.distributed.initialize`` from args or env
    (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID). No-op when
    single-process."""
    global _initialized
    if _initialized:
        return False
    if coordinator_address is None:
        coordinator_address = os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = os.environ.get("NUM_PROCESSES")
    # NB: `process_id or env` would drop process 0 — the coordinator
    if process_id is None:
        process_id = os.environ.get("PROCESS_ID")
    if not coordinator_address or num_processes is None or process_id is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes),
        process_id=int(process_id),
    )
    _initialized = True
    return True


def is_distributed() -> bool:
    return jax.process_count() > 1


def global_mesh_devices():
    """All devices across hosts, ordered for mesh construction."""
    return jax.devices()
