"""Multi-host (DCN) initialization hooks.

Within a slice, collectives ride ICI and need no setup beyond the mesh.
Across hosts (e.g. a v5e-64 spanning multiple workers — BASELINE.json
config 5), JAX needs ``jax.distributed.initialize`` before first use;
these wrappers gate that so single-host usage (and CPU test meshes) is
untouched. The filesystem remains the durable inter-round channel, as
in the reference's crash-only design (lf_das.py:214-217)."""

from __future__ import annotations

import os

import jax

__all__ = ["initialize_multihost", "is_distributed", "global_mesh_devices"]

_initialized = False


def initialize_multihost(
    coordinator_address=None, num_processes=None, process_id=None
):
    """Idempotent ``jax.distributed.initialize`` from args or env
    (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID). No-op when
    single-process."""
    global _initialized
    if _initialized:
        return False
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    num_processes = num_processes or os.environ.get("NUM_PROCESSES")
    process_id = process_id or os.environ.get("PROCESS_ID")
    if not coordinator_address or num_processes is None or process_id is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes),
        process_id=int(process_id),
    )
    _initialized = True
    return True


def is_distributed() -> bool:
    return jax.process_count() > 1


def global_mesh_devices():
    """All devices across hosts, ordered for mesh construction."""
    return jax.devices()
