"""Device mesh construction."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "device_count"]


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices=None, time_shards=1, axis_names=("time", "ch")) -> Mesh:
    """A 2-D (time, channel) mesh over the first ``n_devices`` devices.

    ``time_shards=1`` (default) gives pure channel sharding — the
    zero-communication layout, first choice since the kernels are
    channel-independent (SURVEY.md §2.4). Raise ``time_shards`` to
    shard long resident blocks along time (halo exchange then rides
    ICI neighbors).
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[: int(n_devices)]
    n = len(devices)
    if n % time_shards != 0:
        raise ValueError(
            f"time_shards={time_shards} must divide device count {n}"
        )
    grid = np.array(devices).reshape(time_shards, n // time_shards)
    return Mesh(grid, axis_names)
