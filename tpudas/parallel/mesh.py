"""Device mesh construction."""

from __future__ import annotations

import os

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "device_count", "resolve_mesh"]


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices=None, time_shards=1, axis_names=("time", "ch")) -> Mesh:
    """A 2-D (time, channel) mesh over the first ``n_devices`` devices.

    ``time_shards=1`` (default) gives pure channel sharding — the
    zero-communication layout, first choice since the kernels are
    channel-independent (SURVEY.md §2.4). Raise ``time_shards`` to
    shard long resident blocks along time (halo exchange then rides
    ICI neighbors).
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[: int(n_devices)]
    n = len(devices)
    if n % time_shards != 0:
        raise ValueError(
            f"time_shards={time_shards} must divide device count {n}"
        )
    grid = np.array(devices).reshape(time_shards, n // time_shards)
    return Mesh(grid, axis_names)


def resolve_mesh(mesh=None, env="TPUDAS_MESH"):
    """Driver-facing mesh resolution: the one place ``mesh=`` /
    ``TPUDAS_MESH=N`` turn into a :class:`jax.sharding.Mesh`.

    - ``Mesh`` instance: returned as-is;
    - int ``N`` (or ``TPUDAS_MESH=N`` when ``mesh is None``): a pure
      channel-sharding mesh over the first N devices
      (:func:`make_mesh` with ``time_shards=1``);
    - ``None`` / ``0`` / ``1``: no mesh (single-device execution).

    Also sets the ``tpudas_parallel_shards`` gauge to the resolved
    channel-shard count (1 when unsharded) so an operator can read the
    active layout off ``/metrics`` without knowing the config.
    """
    if mesh is None:
        raw = os.environ.get(env, "").strip()
        if raw:
            mesh = int(raw)
    if isinstance(mesh, (int, np.integer)):
        n = int(mesh)
        if n < 0:
            raise ValueError(f"mesh device count must be >= 0, got {n}")
        if n > len(jax.devices()):
            raise ValueError(
                f"mesh={n} exceeds the {len(jax.devices())} available "
                "devices"
            )
        mesh = None if n in (0, 1) else make_mesh(n)
    from tpudas.obs.registry import get_registry

    get_registry().gauge(
        "tpudas_parallel_shards",
        "channel shards of the active mesh (1 = unsharded)",
    ).set(1 if mesh is None else int(mesh.shape.get("ch", 1)))
    return mesh
