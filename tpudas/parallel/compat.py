"""JAX version-compat shims for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace, and its replication-check keyword was
renamed ``check_rep`` -> ``check_vma`` in the same window.  Every
shard_map call site in tpudas goes through this wrapper — the single
blessed entrypoint (tests/test_parallel.py lints that no other module
imports shard_map directly) — so the codebase runs unmodified on
either side of the migration.

Verified against the pinned jax (0.4.37: experimental home only,
``check_rep`` keyword).  The top-level-import and ``check_vma``
branches are the FORWARD side of the migration; both keyword mappings
are covered by tests (tests/test_parallel.py::TestShardMapCompat) via
a stand-in signature so neither branch is dead-by-construction, and
the blessed-entrypoint lint there keeps the version-skew surface one
file wide.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6-era top-level export
    from jax import shard_map as _shard_map
except ImportError:  # the long-lived experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters

__all__ = ["shard_map"]


def _rep_kwargs(params, check_vma: bool) -> dict:
    """The replication-check keyword under whichever spelling
    ``params`` (a Signature.parameters mapping) declares.  Split out
    of :func:`shard_map` so tests can drive BOTH spellings against a
    stand-in signature on any installed jax."""
    if "check_vma" in params:
        return {"check_vma": check_vma}
    if "check_rep" in params:
        return {"check_rep": check_vma}
    return {}


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` with the keyword spelling of whichever JAX is
    installed (``check_vma`` here maps onto ``check_rep`` on older
    versions — same semantics, renamed upstream)."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **_rep_kwargs(_PARAMS, check_vma),
    )
