"""JAX version-compat shims for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace, and its replication-check keyword was
renamed ``check_rep`` -> ``check_vma`` in the same window.  Every
shard_map call site in tpudas goes through this wrapper so the codebase
runs unmodified on either side of the migration.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6-era top-level export
    from jax import shard_map as _shard_map
except ImportError:  # the long-lived experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` with the keyword spelling of whichever JAX is
    installed (``check_vma`` here maps onto ``check_rep`` on older
    versions — same semantics, renamed upstream)."""
    kwargs = {}
    if "check_vma" in _PARAMS:
        kwargs["check_vma"] = check_vma
    elif "check_rep" in _PARAMS:
        kwargs["check_rep"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
