"""Channel-axis sharding: the zero-communication layout.

Every tpudas kernel operates independently per channel, so a
``(time, channel)`` block sharded as ``P(None, "ch")`` runs the jitted
kernels with NO collectives — XLA partitions the FFT / gather /
reduce_window column-wise automatically. This is the first-choice
production layout (BASELINE.json: "channels sharded over v5e-8")."""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["channel_sharding", "shard_channels"]


def channel_sharding(mesh, ch_axis="ch") -> NamedSharding:
    """Sharding for a (time, channel) array: replicate time, split
    channels over every mesh axis-size along ``ch_axis``."""
    return NamedSharding(mesh, P(None, ch_axis))


def shard_channels(array, mesh, ch_axis="ch"):
    """Place a (T, C) array with channels sharded over the mesh."""
    return jax.device_put(array, channel_sharding(mesh, ch_axis))
