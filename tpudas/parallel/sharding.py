"""Channel-axis sharding: the zero-communication layout.

Every tpudas kernel operates independently per channel, so a
``(time, channel)`` block sharded as ``P(None, "ch")`` runs the jitted
kernels with NO collectives — XLA partitions the FFT / gather /
reduce_window column-wise automatically. This is the first-choice
production layout (BASELINE.json: "channels sharded over v5e-8").

Non-divisible channel counts take the **pad-and-mask** layout (the
alternative — a ragged last shard — would compile a distinct kernel
per shard shape): the channel axis is zero-padded up to a multiple of
the shard count before placement and the pad columns are dropped when
a result is gathered back (:func:`pad_channels` / the ``n_ch`` trim in
callers).  Padding with zeros is exact for every tpudas kernel —
channels are independent, so the real columns never see the pad — and
a zero input column stays zero through the linear filters, so padded
carry state trims back to the unpadded bytes."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span

__all__ = [
    "channel_sharding",
    "shard_channels",
    "channel_pad",
    "pad_channels",
    "place_block",
    "place_carry_leaves",
    "gather_leaves",
    "is_device_resident",
]


def _count_transfer(direction: str, nbytes: int) -> None:
    """Host<->device traffic accounting for the sharded stream path:
    the bench reads these to prove the steady round no longer
    round-trips the carry pytree through host memory."""
    get_registry().counter(
        "tpudas_parallel_transfer_bytes_total",
        "bytes explicitly moved between host and the mesh by the "
        "sharded streaming path",
        labelnames=("direction",),
    ).inc(int(nbytes), direction=direction)


def channel_sharding(mesh, ch_axis="ch") -> NamedSharding:
    """Sharding for a (time, channel) array: replicate time, split
    channels over every mesh axis-size along ``ch_axis``."""
    return NamedSharding(mesh, P(None, ch_axis))


def shard_channels(array, mesh, ch_axis="ch"):
    """Place a (T, C) array with channels sharded over the mesh."""
    return jax.device_put(array, channel_sharding(mesh, ch_axis))


def channel_pad(n_ch: int, mesh, ch_axis="ch") -> int:
    """Zero columns appended to an ``n_ch``-channel array so the
    channel axis splits evenly over the mesh (pad-and-mask layout)."""
    return -int(n_ch) % int(mesh.shape[ch_axis])


def pad_channels(x, mesh, ch_axis="ch"):
    """Zero-pad the channel axis (last axis) of ``x`` to the shard
    multiple.  Host arrays pad on host (cheap, pre-transfer); traced /
    device arrays pad with jnp."""
    pad = channel_pad(np.shape(x)[-1], mesh, ch_axis)
    if not pad:
        return x
    widths = [(0, 0)] * (np.ndim(x) - 1) + [(0, pad)]
    if isinstance(x, np.ndarray):
        return np.pad(x, widths)
    return jnp.pad(x, widths)


def place_block(x, mesh, ch_axis="ch", keep_dtype=False):
    """Pad-and-place one (T, C) input block for the sharded stream
    step: channels split over ``ch_axis``, time replicated.  The
    explicit ``device_put`` (vs letting jit transfer lazily) keeps the
    H2D cost visible under the ``parallel.place`` span.

    ``keep_dtype=True`` places the block in its NATIVE dtype (the raw
    int16 quantized ingest path: half the H2D bytes, dequantization
    happens inside the first kernel); the default converts to float32
    as every pre-quantized-path caller expects."""
    with span("parallel.place", rows=int(np.shape(x)[0])):
        host = np.asarray(x) if keep_dtype else np.asarray(x, np.float32)
        padded = pad_channels(host, mesh, ch_axis)
        _count_transfer("place", padded.nbytes)
        return shard_channels(padded, mesh, ch_axis)


def place_carry_leaves(bufs, mesh, ch_axis="ch"):
    """Pad-and-place a tuple of per-stage carry leaves ((p_i, C)
    each) onto the mesh — used once at stream open / resume; after
    that the leaves live on-device (the stream step returns sharded
    leaves and the driver only gathers on the save cadence)."""
    sharding = channel_sharding(mesh, ch_axis)
    with span("parallel.place", leaves=len(bufs)):
        out = []
        for b in bufs:
            padded = pad_channels(np.asarray(b, np.float32), mesh, ch_axis)
            _count_transfer("place", padded.nbytes)
            out.append(jax.device_put(padded, sharding))
        return tuple(out)


def is_device_resident(x) -> bool:
    """True for a jax device array (the sharded carry leaves the
    stream step returns), False for host numpy — what save cadences
    and the bench use to tell a gather apart from a no-op copy."""
    return isinstance(x, jax.Array)


def gather_leaves(bufs, n_ch: int | None = None):
    """Gather a tuple of (possibly sharded, possibly pad-and-masked)
    carry leaves back to host numpy, trimming the channel axis to the
    logical ``n_ch`` — the serialization form: byte-identical to the
    leaves a single-device run carries.  Host traffic is counted
    (``tpudas_parallel_transfer_bytes_total{direction="gather"}``)
    and the call runs under the ``parallel.gather`` span so the save
    cadence's D2H cost is visible."""
    moved = sum(int(np.size(b)) * 4 for b in bufs if is_device_resident(b))
    with span("parallel.gather", leaves=len(bufs)):
        if moved:
            _count_transfer("gather", moved)
        out = []
        for b in bufs:
            arr = np.asarray(b, np.float32)
            if n_ch is not None and arr.ndim == 2 and arr.shape[1] > n_ch:
                arr = np.ascontiguousarray(arr[:, : int(n_ch)])
            out.append(arr)
        return tuple(out)
