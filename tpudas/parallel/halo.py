"""Halo exchange for a sharded time axis.

The overlap-save edge buffer is a halo (SURVEY.md §5, long-context):
when the time axis of a resident block is sharded across devices, each
shard needs ``halo`` samples from its neighbors before filtering so the
trimmed interior is seam-free. ``lax.ppermute`` moves the halos over
ICI neighbor links (ring topology — the same primitive ring attention
uses); boundary shards receive zeros, which is exactly the zero-padded
stream-boundary semantics the host-side engine has.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["exchange_halo_time"]


def exchange_halo_time(block, halo: int, axis_name: str = "time",
                       n_shards: int | None = None,
                       left: bool = True, right: bool = True):
    """Inside shard_map: return block extended with neighbor halos.

    block: (T_local, ...) — the local time shard. Returns the block
    extended by ``halo`` rows on each requested side; call sites trim
    the processed result to keep only valid interior. A one-sided
    exchange (``left=False`` for a causal consumer that only looks
    ahead) runs a single ppermute — half the ICI traffic.
    """
    if halo <= 0 or not (left or right):
        return block
    if halo > block.shape[0]:
        raise ValueError(
            f"halo ({halo}) exceeds the local time-shard length "
            f"({block.shape[0]}); use fewer time shards or a longer block"
        )
    if n_shards is None:
        n_shards = jax.lax.axis_size(axis_name)
    if n_shards == 1:
        pad = jnp.zeros((halo,) + block.shape[1:], block.dtype)
        parts = [pad] if left else []
        parts.append(block)
        if right:
            parts.append(pad)
        return jnp.concatenate(parts, axis=0) if len(parts) > 1 else block
    fwd = [(i, i + 1) for i in range(n_shards - 1)]
    bwd = [(i + 1, i) for i in range(n_shards - 1)]
    # my tail -> right neighbor's left halo; my head -> left neighbor's
    # right halo. Unmatched shards (stream boundaries) receive zeros.
    parts = []
    if left:
        parts.append(jax.lax.ppermute(block[-halo:], axis_name, fwd))
    parts.append(block)
    if right:
        parts.append(jax.lax.ppermute(block[:halo], axis_name, bwd))
    return jnp.concatenate(parts, axis=0)
