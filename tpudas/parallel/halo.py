"""Halo exchange for a sharded time axis.

The overlap-save edge buffer is a halo (SURVEY.md §5, long-context):
when the time axis of a resident block is sharded across devices, each
shard needs ``halo`` samples from its neighbors before filtering so the
trimmed interior is seam-free. ``lax.ppermute`` moves the halos over
ICI neighbor links (ring topology — the same primitive ring attention
uses); boundary shards receive zeros, which is exactly the zero-padded
stream-boundary semantics the host-side engine has.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["exchange_halo_time", "fir_halo_rows"]


def fir_halo_rows(plan, n_loc: int, n_ch_local: int = 1,
                  engine: str = "auto") -> int:
    """One-sided (look-ahead) halo width, in full-rate input rows, a
    time shard must receive from its right neighbor so its ``n_loc``
    local cascade outputs have their complete filter support.

    The math from the taps: a stage with ``len(h)`` taps and ratio
    ``R`` reads ``ceil(len(h)/R)`` frames per output, so producing
    ``k`` outputs consumes ``(k + B - 1) * R`` inputs with
    ``B = ceil(len(h)/R)`` — telescoped over the cascade this is
    :func:`tpudas.ops.fir.chain_layout`'s input-rows number, and the
    halo is whatever exceeds the shard's own ``n_loc * ratio`` rows
    (Pallas stages consume grid-rounded inputs, so ``n_ch_local`` /
    ``engine`` must describe the layout the shard body will trace).
    Matches ``tpudas.parallel.pipeline.sharded_cascade_layout``.
    """
    from tpudas.ops.fir import chain_layout

    _, rows_local = chain_layout(plan, int(n_loc), int(n_ch_local), engine)
    return rows_local - int(n_loc) * int(plan.ratio)


def exchange_halo_time(block, halo: int, axis_name: str = "time",
                       n_shards: int | None = None,
                       left: bool = True, right: bool = True):
    """Inside shard_map: return block extended with neighbor halos.

    block: (T_local, ...) — the local time shard. Returns the block
    extended by ``halo`` rows on each requested side; call sites trim
    the processed result to keep only valid interior. A one-sided
    exchange (``left=False`` for a causal consumer that only looks
    ahead) runs a single ppermute — half the ICI traffic.
    """
    if halo <= 0 or not (left or right):
        return block
    if halo > block.shape[0]:
        raise ValueError(
            f"halo ({halo}) exceeds the local time-shard length "
            f"({block.shape[0]}); use fewer time shards or a longer block"
        )
    if n_shards is None:
        n_shards = jax.lax.axis_size(axis_name)
    if n_shards == 1:
        pad = jnp.zeros((halo,) + block.shape[1:], block.dtype)
        parts = [pad] if left else []
        parts.append(block)
        if right:
            parts.append(pad)
        return jnp.concatenate(parts, axis=0) if len(parts) > 1 else block
    fwd = [(i, i + 1) for i in range(n_shards - 1)]
    bwd = [(i + 1, i) for i in range(n_shards - 1)]
    # my tail -> right neighbor's left halo; my head -> left neighbor's
    # right halo. Unmatched shards (stream boundaries) receive zeros.
    parts = []
    if left:
        parts.append(jax.lax.ppermute(block[-halo:], axis_name, fwd))
    parts.append(block)
    if right:
        parts.append(jax.lax.ppermute(block[:halo], axis_name, bwd))
    return jnp.concatenate(parts, axis=0)
