"""Parallel execution over TPU meshes.

The reference is single-process (SURVEY.md §2.4); its implicit structure
becomes explicit here, the TPU way:

- **channel sharding** (zero-communication): every kernel is per-channel
  1-D DSP, so sharding the channel axis over the mesh needs no
  collectives at all — XLA partitions the jitted kernels automatically
  given sharded inputs (:mod:`tpudas.parallel.sharding`).
- **time/sequence sharding** with halo exchange: the engine's edge
  buffer IS a halo; when the time axis is sharded, neighbors exchange
  halos over ICI with ``lax.ppermute`` inside ``shard_map``
  (:mod:`tpudas.parallel.halo`, :mod:`tpudas.parallel.pipeline`).
- **data parallelism over patches/windows**: independent spool patches
  batch into a leading axis sharded over devices
  (:mod:`tpudas.parallel.batch`).
- **multi-host** over DCN via ``jax.distributed``
  (:mod:`tpudas.parallel.distributed`).
"""

from tpudas.parallel.mesh import make_mesh, device_count, resolve_mesh
from tpudas.parallel.sharding import shard_channels, channel_sharding
from tpudas.parallel.halo import exchange_halo_time
from tpudas.parallel.pipeline import sharded_lowpass_decimate
from tpudas.parallel.batch import batched_rolling_mean

__all__ = [
    "make_mesh",
    "device_count",
    "resolve_mesh",
    "shard_channels",
    "channel_sharding",
    "exchange_halo_time",
    "sharded_lowpass_decimate",
    "batched_rolling_mean",
]
