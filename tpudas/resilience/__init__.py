"""tpudas.resilience: fault isolation for the unattended edge driver.

The paper's deployment target is an operator-less box at the
interrogator; PR 1 made the realtime loop crash-only (kill it anywhere,
the next run resumes seam-free) and PR 2 made it observable.  This
package closes the remaining gap: a crash should not be the ANSWER to
every fault.  Three pieces:

- :mod:`tpudas.resilience.faults` — failure taxonomy
  (transient / corrupt / fatal), deterministic capped-exponential
  retry/backoff (:class:`RetryPolicy`), the per-round
  :class:`FaultBoundary` the realtime drivers run their rounds inside,
  and the deterministic fault-injection harness (:class:`FaultPlan`)
  that lets tier-1 tests exercise every degradation path;
- :mod:`tpudas.resilience.quarantine` — the bad-file ledger
  (``.quarantine.json`` beside the stream carry): a file that fails to
  read/decode N times is excluded from the spool index and retried on
  a slow schedule in case the interrogator finishes writing it late.

See RESILIENCE.md for the failure taxonomy, retry policy, ledger
format, and the operator runbook for ``degraded`` health states.
"""

from tpudas.resilience.faults import (
    FAULT_SITES,
    FaultBoundary,
    FaultPlan,
    FaultSpec,
    RetryPolicy,
    SpoolReadError,
    TransientFaultError,
    classify_failure,
    fault_point,
    install_fault_plan,
)
from tpudas.resilience.quarantine import QUARANTINE_FILENAME, QuarantineLedger

__all__ = [
    "FAULT_SITES",
    "FaultBoundary",
    "FaultPlan",
    "FaultSpec",
    "RetryPolicy",
    "SpoolReadError",
    "TransientFaultError",
    "classify_failure",
    "fault_point",
    "install_fault_plan",
    "QUARANTINE_FILENAME",
    "QuarantineLedger",
]
