"""Failure taxonomy, retry/backoff, the per-round fault boundary, and
the deterministic fault-injection harness.

Taxonomy (:func:`classify_failure`):

- ``"transient"`` — environmental IO that a later attempt can succeed
  at: an NFS hiccup in the index re-scan, a file the interrogator is
  still flushing, a momentary ``OSError`` anywhere in the round.  The
  boundary retries these with capped exponential backoff + jitter.
- ``"corrupt"`` — the input itself is bad: a file whose payload fails
  to decode (:class:`SpoolReadError` wrapping a non-OS error).  The
  round is retried too, but every corrupt failure is charged to the
  offending file in the quarantine ledger; after
  ``RetryPolicy.quarantine_after`` strikes the file is excluded from
  the spool index and the round proceeds without it.
- ``"network"`` — the remote storage tier answered badly or not at
  all: any :class:`NetworkFaultError` (connection reset, 5xx, timeout,
  dropped response from an object-store backend).  Retried like a
  transient, but kept distinct in metrics/ledgers because the remedy
  differs — a network storm wants capped-exponential patience and the
  cold-tier degradation ladder (:mod:`tpudas.store.cache`), not
  quarantine: the bytes are fine, the wire is not.
- ``"resource"`` — the disk (or quota) is full: ``OSError`` with
  ``ENOSPC``/``EDQUOT``.  Retried like a transient but with extra
  patience (``max_consecutive * resource_patience`` attempts — a full
  disk usually clears when rotation kicks in, and dying does not free
  space); the boundary additionally flips the process-wide pressure
  flag (:mod:`tpudas.integrity.resource`) so the driver sheds
  non-essential writers (pyramid append, metrics.prom) until a probe
  write succeeds again.
- ``"fatal"`` — configuration or programming errors (``TypeError``,
  ``ValueError`` outside a file read, the reference's ``on_gap="raise"``
  gap exception).  Retrying cannot help; these propagate immediately,
  exactly as every exception did before this module existed.

Backoff is DETERMINISTIC: ``RetryPolicy.delay(attempt)`` derives its
jitter from a tiny LCG over ``(seed, attempt)``, so tests (and
post-mortems) can predict every sleep to the microsecond.

The fault-injection harness is three names: :class:`FaultSpec` (what to
do, where, on which hit), :class:`FaultPlan` (an ordered set of specs
plus the fired log), and :func:`install_fault_plan` (scope it over a
block).  Production code marks its fault sites with
:func:`fault_point`; with no plan installed the site costs one global
``is None`` check.  Sites (:data:`FAULT_SITES`):

- ``"spool.read"`` — per-file payload read (tpudas/io/spool.py);
- ``"index.update"`` — the directory index re-scan (tpudas/io/index.py);
- ``"round.body"`` — top of each realtime processing round
  (tpudas/proc/streaming.py);
- ``"carry.save"`` — the stream-carry persist (tpudas/proc/stream.py);
- ``"stream.prefetch"`` — the async-ingest producer, before each
  speculative slice load (tpudas/proc/ingest.py): a kill here proves
  a prefetched-but-uncommitted slice is crash-equivalent to
  never-read;
- ``"serve.tile_read"`` — per-tile pyramid read (tpudas/serve/tiles.py);
- ``"serve.queue_full"`` — the HTTP admission gate (tpudas/serve/http.py):
  an injected fault here reads as "gate saturated", so load-shed paths
  are testable without racing real threads;
- ``"integrity.verify"`` — the head of every verified artifact read
  (tpudas/integrity/checksum.py): ``action="truncate"`` here corrupts
  the artifact an instant before its checksum check, so every
  degradation ladder is drillable byte-for-byte;
- ``"fs.write_enospc"`` — every atomic state write
  (tpudas/utils/atomicio.py) plus the recovery probe
  (tpudas/integrity/resource.py): raise ``OSError(ENOSPC)`` here (see
  ``tpudas.testing.enospc_error``) and the process experiences a full
  disk, degradation ladder included;
- ``"detect.op"`` — the head of every detect-operator ``process``
  call (tpudas/detect/runner.py): an injected fault here is counted,
  the round's detect commit is skipped, and the rows replay via
  catch-up next round — the stream itself never notices;
- ``"detect.ledger_write"`` — the events-ledger rewrite
  (tpudas/detect/ledger.py): kill here and the resumed pipeline
  truncates the ledger back to the detect carry and regenerates the
  lost lines byte-identically.
- ``"backfill.claim"`` — the head of a shard-lease claim/steal write
  (tpudas/backfill/queue.py): a raise here is a worker dying with its
  claim half-made — the lease either never lands (shard stays open)
  or lands and goes stale, and either way another worker reclaims it;
- ``"backfill.commit"`` — just before a shard's (or the stitch's)
  atomic staging→final rename (tpudas/backfill/queue.py /
  stitch.py): a kill here orphans the fully-drained staging directory
  (swept by ``audit_backfill``) and the shard is re-executed — the
  exactly-once guarantee is the commit-wins rename, not the worker.
- ``"obs.flight_write"`` — the flight recorder's per-round segment
  flush (tpudas/obs/flight.py): a raise here is dropped + counted
  (the trace must never take down the stream), and a
  ``KeyboardInterrupt`` kill models a crash mid-flush — the readers
  and the audit recover the segment's verified prefix.
- ``"store.op"`` — the head of every object-store backend call
  (tpudas/store/base.py), BEFORE the backend touches anything: a
  raise here is a clean 5xx/unavailable — the operation never
  applied, a blind retry is always safe.
- ``"store.op.sent"`` — after a store mutation (put/CAS/delete)
  APPLIED but before its token returns: a raise here is a **dropped
  response** — the write landed, the caller never heard.  The
  lost-CAS drill lives at this site; recovery is the token re-read in
  :mod:`tpudas.store.retry`.  Context carries ``path`` (the object
  key) and ``op`` so ``match=`` can target one artifact class.
"""

from __future__ import annotations

import errno
import os
import time
from dataclasses import dataclass, field

from tpudas.obs.registry import get_registry
from tpudas.utils.logging import log_event

__all__ = [
    "FAULT_SITES",
    "FaultBoundary",
    "FaultPlan",
    "FaultSpec",
    "NetworkFaultError",
    "RetryPolicy",
    "SpoolReadError",
    "TransientFaultError",
    "classify_failure",
    "fault_point",
    "install_fault_plan",
]


class TransientFaultError(OSError):
    """An injected (or explicitly tagged) transient fault — an
    ``OSError`` so the taxonomy needs no special case for it."""


class NetworkFaultError(OSError):
    """A remote storage/network failure — the taxonomy's ``"network"``
    kind.  Defined here (not in tpudas.store) so
    :func:`classify_failure` needs no import of the store package;
    ``tpudas.store.base.StoreNetworkError`` subclasses this."""


class SpoolReadError(Exception):
    """A per-file payload read/decode failure, carrying the offending
    path so the fault boundary can charge the quarantine ledger.
    Raised by ``DirectorySpool._read_row`` around any reader error;
    ``__cause__`` holds the original exception."""

    def __init__(self, path: str, original: BaseException):
        super().__init__(
            f"failed to read {path!r}: "
            f"{type(original).__name__}: {original}"
        )
        self.path = str(path)
        self.original = original


RESOURCE_ERRNOS = (errno.ENOSPC, errno.EDQUOT)


def classify_failure(exc: BaseException) -> str:
    """``"transient"`` | ``"corrupt"`` | ``"network"`` | ``"resource"``
    | ``"fatal"`` for one exception.

    A :class:`SpoolReadError` wrapping an ``OSError`` is transient (the
    interrogator may still be flushing the file); wrapping anything
    else it is corrupt (the bytes decoded wrong — rereading the same
    bytes cannot fix that, only quarantine can).  A
    :class:`NetworkFaultError` is network (the remote storage tier
    misbehaved — retried with backoff, never quarantined).  An
    ``OSError`` with ``ENOSPC``/``EDQUOT`` is resource (the OUTPUT
    side is full — retrying with shed writers beats dying); any other
    bare ``OSError`` in the round is transient.  Everything else —
    config, programming, the reference's gap raise — is fatal.
    """
    if isinstance(exc, SpoolReadError):
        return (
            "transient" if isinstance(exc.original, OSError) else "corrupt"
        )
    if isinstance(exc, MemoryError):
        return "fatal"
    if isinstance(exc, NetworkFaultError):
        return "network"
    if isinstance(exc, OSError):
        if getattr(exc, "errno", None) in RESOURCE_ERRNOS:
            return "resource"
        return "transient"
    return "fatal"


@dataclass(frozen=True)
class RetryPolicy:
    """Per-round retry/backoff + quarantine thresholds.

    ``delay(attempt)`` is ``base_delay * multiplier**attempt`` capped at
    ``max_delay``, plus a deterministic jitter in
    ``[0, jitter * delay]`` derived from ``(seed, attempt)`` — no RNG
    state, no wall clock, fully predictable for tests.
    """

    max_consecutive: int = 8  # round failures before even transients propagate
    base_delay: float = 1.0  # seconds, first retry
    max_delay: float = 60.0  # backoff cap
    multiplier: float = 2.0
    jitter: float = 0.1  # fraction of the capped delay
    seed: int = 0
    quarantine_after: int = 3  # per-file strikes before quarantine
    quarantine_retry: float = 900.0  # slow-schedule probe interval (s)
    # resource (disk-full) failures get max_consecutive * this before
    # propagating: exiting cannot free space, waiting for rotation can
    resource_patience: int = 8
    clock: object = time.time  # injectable for deterministic tests

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based)."""
        d = min(
            self.base_delay * self.multiplier ** max(int(attempt), 0),
            self.max_delay,
        )
        # LCG over (seed, attempt): deterministic jitter in [0, jitter*d]
        x = (
            (int(self.seed) * 1103515245 + int(attempt) * 12345 + 12821)
            % (1 << 31)
        ) / float(1 << 31)
        return d * (1.0 + self.jitter * x)


@dataclass
class FaultDecision:
    """What the boundary decided about one round failure."""

    kind: str  # transient | corrupt | fatal
    propagate: bool
    delay: float = 0.0  # backoff before the retry (when not propagating)
    reason: str = ""


class FaultBoundary:
    """Per-run fault bookkeeping for a realtime driver.

    One instance per driver run; the driver funnels every round failure
    through :meth:`on_failure` and every completed round through
    :meth:`on_success`.  The boundary classifies, charges file-
    attributed failures to the quarantine ledger, decides
    retry-vs-propagate, and keeps the degradation metrics/health fields
    current (``tpudas_stream_consecutive_failures``,
    ``tpudas_stream_degraded``, ``tpudas_stream_quarantined_files``).
    """

    def __init__(self, policy: RetryPolicy | None = None, ledger=None):
        self.policy = policy if policy is not None else RetryPolicy()
        self.ledger = ledger
        self.consecutive = 0  # failed round attempts since last success
        self.retries = 0  # total retries this run
        self.last_error: str | None = None

    # -- state the driver surfaces in health.json ----------------------
    @property
    def quarantined_count(self) -> int:
        return 0 if self.ledger is None else self.ledger.quarantined_count

    @property
    def degraded(self) -> bool:
        return self.consecutive > 0 or self.quarantined_count > 0

    def excluded_now(self):
        """Basenames the spool must exclude this round (quarantined
        files whose slow-retry window has not yet opened)."""
        if self.ledger is None:
            return frozenset()
        return self.ledger.excluded(now=self.policy.clock())

    # -- the round preamble (shared by both realtime drivers) ----------
    def begin_round(self, sp, source):
        """Start one polling round over a freshly created spool:
        apply the quarantine exclusion, ``update()`` the index, charge
        scan failures (the file is skipped, the round continues), and
        run the slow-schedule probe bookkeeping.  Returns the updated
        spool.

        Probe release is by failure source: a SCAN-sourced entry whose
        scan now passes is released on the spot (the interrogator
        finished writing it); a READ-sourced entry (scan always
        passed — the payload was the problem) is only *marked pending*
        and released by :meth:`on_success` when the round completes —
        a failed probe read instead re-quarantines WITH escalation,
        the entry's backoff history intact."""
        excl = self.excluded_now()
        if excl and hasattr(sp, "exclude"):
            sp = sp.exclude(excl)
        sp = sp.update()
        scan_errors = getattr(sp, "scan_errors", None) or {}
        for name, msg in scan_errors.items():
            self._charge_file(
                os.path.join(str(source), name), msg, source="scan"
            )
        if self.ledger is not None and self.ledger.quarantined_count:
            for name in self.ledger.probe_open_names(self.policy.clock()):
                # a probe whose scan failed was just re-quarantined by
                # the charge above and is no longer probe-open
                entry = self.ledger.entry(name) or {}
                if entry.get("source") == "read":
                    self.ledger.mark_probe_pending(name)
                else:
                    self._release(name)
        return sp

    # -- the boundary itself -------------------------------------------
    def on_success(self) -> None:
        if self.consecutive:
            log_event("stream_round_recovered", after=self.consecutive)
        self.consecutive = 0
        self.last_error = None
        if self.ledger is not None:
            # read-sourced probes that rode this round to completion:
            # the payload read succeeded (or the file failed and was
            # re-quarantined before we got here)
            for name in self.ledger.probe_pending_names():
                self._release(name)
        self._gauges()

    def on_failure(self, exc: BaseException, where: str = "round") -> (
        FaultDecision
    ):
        kind = classify_failure(exc)
        self.last_error = f"{type(exc).__name__}: {str(exc)[:300]}"
        reg = get_registry()
        reg.counter(
            "tpudas_stream_round_failures_total",
            "realtime round attempts that raised, by failure kind",
            labelnames=("kind",),
        ).inc(kind=kind)
        if isinstance(exc, SpoolReadError):
            self._charge_file(exc.path, self.last_error)
        if kind == "resource":
            # flip the process-wide pressure flag: the driver sheds
            # non-essential writers until a probe write succeeds
            from tpudas.integrity.resource import note_pressure

            note_pressure(where, exc)
        if kind == "fatal":
            decision = FaultDecision(kind, True, reason="fatal failure")
        else:
            self.consecutive += 1
            self._gauges()
            limit = self.policy.max_consecutive
            if kind == "resource":
                limit *= max(int(self.policy.resource_patience), 1)
            if self.consecutive > limit:
                decision = FaultDecision(
                    kind,
                    True,
                    reason=(
                        f"{self.consecutive} consecutive round failures "
                        f"(max {limit})"
                    ),
                )
            else:
                self.retries += 1
                reg.counter(
                    "tpudas_stream_retries_total",
                    "round retries scheduled by the fault boundary",
                ).inc()
                decision = FaultDecision(
                    kind, False, delay=self.policy.delay(self.consecutive - 1)
                )
        log_event(
            "stream_round_failed",
            where=where,
            kind=kind,
            error=self.last_error,
            consecutive=self.consecutive,
            propagate=decision.propagate,
            retry_delay_s=round(decision.delay, 3),
        )
        return decision

    # -- internals ------------------------------------------------------
    def _charge_file(self, path: str, msg: str, source: str = "read") -> (
        None
    ):
        if self.ledger is None:
            return
        outcome = self.ledger.record_failure(
            path, msg, now=self.policy.clock(),
            threshold=self.policy.quarantine_after,
            retry_interval=self.policy.quarantine_retry,
            source=source,
        )
        if outcome == "added":
            get_registry().counter(
                "tpudas_stream_quarantine_added_total",
                "files newly quarantined by the fault boundary",
            ).inc()
        elif outcome == "requarantined":
            get_registry().counter(
                "tpudas_stream_quarantine_requarantined_total",
                "failed slow-schedule probes (re-quarantined with "
                "escalated backoff)",
            ).inc()
        self._gauge_quarantine()

    def _release(self, name: str) -> None:
        self.ledger.record_success(name)
        self._gauge_quarantine()

    def _gauge_quarantine(self) -> None:
        get_registry().gauge(
            "tpudas_stream_quarantined_files",
            "source files currently quarantined (excluded from the index)",
        ).set(self.quarantined_count)

    def _gauges(self) -> None:
        reg = get_registry()
        reg.gauge(
            "tpudas_stream_consecutive_failures",
            "failed round attempts since the last completed round",
        ).set(self.consecutive)
        reg.gauge(
            "tpudas_stream_degraded",
            "1 while the driver is retrying or has quarantined files",
        ).set(1.0 if self.degraded else 0.0)


# ---------------------------------------------------------------------------
# deterministic fault injection

FAULT_SITES = (
    "spool.read",
    "index.update",
    "round.body",
    "carry.save",
    "stream.prefetch",
    "serve.tile_read",
    "serve.queue_full",
    "integrity.verify",
    "fs.write_enospc",
    "detect.op",
    "detect.ledger_write",
    "backfill.claim",
    "backfill.commit",
    "obs.flight_write",
    "store.op",
    "store.op.sent",
    "live.emit",
)

_ACTIONS = ("raise", "truncate", "delay")


@dataclass
class FaultSpec:
    """One injected fault: fire ``action`` at hits
    ``[at, at + times)`` of ``site`` (1-based hit counting).

    - ``action="raise"`` raises ``exc`` (class or instance; default
      :class:`TransientFaultError`, i.e. classified transient);
    - ``action="truncate"`` truncates the file in the site's ``path``
      context to ``nbytes`` (a half-written interrogator file) and lets
      execution continue into the natural decode failure;
    - ``action="delay"`` calls ``sleep_fn(seconds)`` (default
      ``time.sleep``) — a slow NFS mount, not a failure.

    ``match`` (substring) additionally gates the spec on the site's
    path-like context (``path``/``folder``/``directory``), so a fault
    can target ONE file while other reads at the same site succeed.
    Hit counting stays per-site and global regardless of ``match``.
    """

    site: str
    action: str = "raise"
    at: int = 1
    times: int = 1
    exc: object = None
    nbytes: int = 0
    seconds: float = 0.0
    sleep_fn: object = None
    match: str | None = None

    def __post_init__(self):
        if self.site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; known: {FAULT_SITES}"
            )
        if self.action not in _ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r}; known: {_ACTIONS}"
            )


class FaultPlan:
    """An ordered set of :class:`FaultSpec` plus per-site hit counters
    and the ``fired`` log (``(site, action, hit_index)`` tuples) tests
    assert against.  Install with :func:`install_fault_plan`."""

    def __init__(self, *specs: FaultSpec):
        self.specs = list(specs)
        self.hits: dict = {site: 0 for site in FAULT_SITES}
        self.fired: list = []

    def hit(self, site: str, ctx: dict) -> None:
        self.hits[site] = n = self.hits.get(site, 0) + 1
        where = str(
            ctx.get("path") or ctx.get("folder") or ctx.get("directory")
            or ""
        )
        for spec in self.specs:
            if spec.site != site or not (
                spec.at <= n < spec.at + spec.times
            ):
                continue
            if spec.match is not None and spec.match not in where:
                continue
            self.fired.append((site, spec.action, n))
            if spec.action == "delay":
                (spec.sleep_fn or time.sleep)(spec.seconds)
            elif spec.action == "truncate":
                path = ctx.get("path") or ctx.get("folder")
                if path and os.path.isfile(path):
                    with open(path, "r+b") as fh:
                        fh.truncate(int(spec.nbytes))
            else:  # raise
                exc = spec.exc
                if exc is None:
                    exc = TransientFaultError(
                        f"injected transient fault at {site} (hit {n})"
                    )
                elif isinstance(exc, type):
                    exc = exc(f"injected fault at {site} (hit {n})")
                raise exc


_PLAN: FaultPlan | None = None


def fault_point(site: str, **ctx) -> None:
    """Marks a fault-injection site in production code.  No plan
    installed (the always case outside tests) costs one global ``is
    None`` check."""
    if _PLAN is not None:
        _PLAN.hit(site, ctx)


class install_fault_plan:
    """``with install_fault_plan(plan): ...`` scopes a
    :class:`FaultPlan` over a block (process-global — the drivers run
    worker threads; tests do not run concurrently).  Also usable as
    ``install_fault_plan(plan)`` / ``install_fault_plan(None)`` pairs.
    """

    def __init__(self, plan: FaultPlan | None):
        global _PLAN
        self._prev = _PLAN
        _PLAN = plan

    def __enter__(self):
        return _PLAN

    def __exit__(self, *exc_info):
        global _PLAN
        _PLAN = self._prev
        return False
