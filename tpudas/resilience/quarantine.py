"""Bad-file quarantine: the ``.quarantine.json`` ledger.

A source file that repeatedly fails to scan or read is almost always
one of two things at an unattended site: a file the interrogator is
STILL WRITING (transient — it will complete), or a file that was
truncated/corrupted for good (permanent).  Distinguishing them from
inside one polling round is impossible, so the ledger does it across
rounds: every failure is a strike; at ``threshold`` strikes the file is
quarantined — excluded from the spool index so the round loop stops
paying for it — and re-probed on a slow schedule (``retry_interval``,
doubling per re-quarantine up to 8x) in case the interrogator finished
writing it late.  Release depends on where the failure surfaced
(``source``): a SCAN-sourced entry is released the moment its scan
passes again; a READ-sourced entry (scan fine, payload bad) is marked
``probe_pending`` and released only when the probing round COMPLETES —
a failed probe read re-quarantines with the entry's backoff history
(``rounds``) intact, so the doubling escalation survives the probe.

The ledger lives beside the stream carry in the OUTPUT folder (one
JSON object, written tmp-then-rename like every other tpudas state
file), so the crash-only contract holds: kill the driver anywhere and
the next run reloads the same quarantine state.  A corrupt ledger
degrades to empty (logged + counted) — quarantine is an optimization,
never a reason to die.
"""

from __future__ import annotations

import os
import time

from tpudas.obs.registry import get_registry
from tpudas.utils.logging import log_event

__all__ = ["QUARANTINE_FILENAME", "QuarantineLedger"]

QUARANTINE_FILENAME = ".quarantine.json"
_VERSION = 1
_MAX_BACKOFF_ROUNDS = 3  # retry interval doubles per round, capped at 8x


class QuarantineLedger:
    """Per-file failure strikes and quarantine state, persisted as
    ``.quarantine.json`` in ``folder``.  Entries are keyed by the
    source file's basename (the spool excludes by basename)."""

    def __init__(self, folder: str):
        self.folder = str(folder)
        self._entries: dict[str, dict] = {}
        self._load()

    # -- persistence ---------------------------------------------------
    @property
    def path(self) -> str:
        return os.path.join(self.folder, QUARANTINE_FILENAME)

    def _load(self) -> None:
        """Verified-read ladder: checksummed primary, then the
        ``.prev`` double buffer, then empty (counted) — quarantine is
        an optimization, never a reason to die."""
        from tpudas.integrity.checksum import (
            count_fallback,
            count_unstamped,
            read_json_verified,
        )

        primary = self.path
        if not os.path.isfile(primary) and not os.path.isfile(
            primary + ".prev"
        ):
            return
        for cand in (primary, primary + ".prev"):
            try:
                raw, status = read_json_verified(cand, "quarantine")
                if status == "mismatch":
                    raise ValueError("ledger checksum mismatch")
                if status == "unstamped":
                    count_unstamped("quarantine")
                if raw.get("version") != _VERSION:
                    log_event(
                        "quarantine_version_skew", got=raw.get("version")
                    )
                    return
                files = raw.get("files", {})
                if not isinstance(files, dict):
                    raise ValueError("files is not a mapping")
                self._entries = {
                    str(k): dict(v) for k, v in files.items()
                }
                return
            except FileNotFoundError:
                continue
            except (OSError, ValueError, TypeError, AttributeError) as exc:
                # a torn/corrupt rung falls through the ladder
                log_event(
                    "quarantine_ledger_unreadable", path=cand,
                    error=str(exc)[:200],
                )
                get_registry().counter(
                    "tpudas_quarantine_ledger_unreadable_total",
                    "corrupt quarantine ledgers degraded to .prev or "
                    "empty",
                ).inc()
                count_fallback("quarantine", str(exc)[:120], cand)
                continue
        self._entries = {}

    def _save(self) -> None:
        from tpudas.integrity.checksum import (
            rotate_prev,
            write_json_checksummed,
        )

        payload = {"version": _VERSION, "files": self._entries}
        try:
            rotate_prev(self.path)
            write_json_checksummed(self.path, payload)
        except OSError as exc:
            # read-only output dir: ledger stays in-memory for this run
            log_event("quarantine_ledger_write_failed", error=str(exc)[:200])

    # -- queries -------------------------------------------------------
    @property
    def quarantined_count(self) -> int:
        return sum(1 for e in self._entries.values() if e.get("quarantined"))

    def quarantined_names(self) -> list[str]:
        return sorted(
            n for n, e in self._entries.items() if e.get("quarantined")
        )

    def entry(self, name_or_path: str) -> dict | None:
        return self._entries.get(os.path.basename(name_or_path))

    def excluded(self, now: float | None = None) -> frozenset:
        """Basenames to exclude from the spool index right now:
        quarantined files whose slow-retry probe window has not opened
        yet."""
        now = time.time() if now is None else float(now)
        return frozenset(
            n
            for n, e in self._entries.items()
            if e.get("quarantined") and now < float(e.get("retry_at", 0.0))
        )

    def probe_open_names(self, now: float | None = None) -> list[str]:
        """Quarantined basenames whose retry window is open (the spool
        will include them this round as a probe)."""
        now = time.time() if now is None else float(now)
        return sorted(
            n
            for n, e in self._entries.items()
            if e.get("quarantined") and now >= float(e.get("retry_at", 0.0))
        )

    def probe_pending_names(self) -> list[str]:
        """Quarantined basenames whose probe is riding the current
        round (see :meth:`mark_probe_pending`)."""
        return sorted(
            n
            for n, e in self._entries.items()
            if e.get("quarantined") and e.get("probe_pending")
        )

    # -- mutations -----------------------------------------------------
    def mark_probe_pending(self, name_or_path: str) -> None:
        """Flag a read-sourced quarantined entry as probing via the
        CURRENT round: its payload is about to be read again.  The
        caller releases it when the round completes (the read
        succeeded); a failure clears the flag and re-quarantines with
        escalation — the entry (and its backoff ``rounds``) survives
        the probe either way."""
        e = self._entries.get(os.path.basename(str(name_or_path)))
        if e is not None and not e.get("probe_pending"):
            e["probe_pending"] = True
            self._save()

    def record_failure(
        self,
        path: str,
        error: str,
        now: float | None = None,
        threshold: int = 3,
        retry_interval: float = 900.0,
        source: str = "read",
    ) -> str | None:
        """One strike against ``path``.  ``source`` records where the
        failure surfaced (``"scan"`` — the index scan; ``"read"`` — a
        payload read), which decides how a later probe can release the
        entry.  Returns ``"added"`` when this strike newly quarantined
        the file, ``"requarantined"`` after a failed probe, else None.
        """
        now = time.time() if now is None else float(now)
        name = os.path.basename(str(path))
        e = self._entries.setdefault(
            name,
            {
                "fails": 0,
                "first_failed_at": now,
                "quarantined": False,
                "rounds": 0,
            },
        )
        e["fails"] = int(e.get("fails", 0)) + 1
        e["last_failed_at"] = now
        e["last_error"] = str(error)[:300]
        e["source"] = str(source)
        e["probe_pending"] = False
        outcome = None
        was_probe = bool(e.get("quarantined")) and now >= float(
            e.get("retry_at", 0.0)
        )
        if was_probe or (
            not e.get("quarantined") and e["fails"] >= int(threshold)
        ):
            # quarantine (or re-quarantine after a failed probe) with a
            # doubling, capped retry interval
            e["quarantined"] = True
            e["rounds"] = rounds = int(e.get("rounds", 0)) + 1
            wait = float(retry_interval) * (
                2 ** min(rounds - 1, _MAX_BACKOFF_ROUNDS)
            )
            e["retry_at"] = now + wait
            outcome = "requarantined" if was_probe else "added"
            log_event(
                "quarantine_added",
                file=name,
                fails=e["fails"],
                rounds=rounds,
                retry_in_s=round(wait, 1),
                error=e["last_error"],
            )
        self._save()
        return outcome

    def record_success(self, name_or_path: str) -> bool:
        """A read/scan of the file succeeded: release it entirely
        (strikes included — a once-flaky file earns a clean slate).
        Returns True when an entry was removed."""
        name = os.path.basename(str(name_or_path))
        e = self._entries.pop(name, None)
        if e is None:
            return False
        if e.get("quarantined"):
            log_event("quarantine_released", file=name, fails=e.get("fails"))
            get_registry().counter(
                "tpudas_stream_quarantine_released_total",
                "quarantined files released after a successful probe",
            ).inc()
        self._save()
        return True
