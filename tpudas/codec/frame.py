"""The self-describing compressed tile container.

On-disk layout of one ``.tpt`` blob::

    b"TPTC"                      4-byte magic
    u32 little-endian            header length H
    H bytes                      canonical JSON header
    payload                      the codec's compressed bytes

Header keys: ``version`` (1), ``codec`` (registry id), ``dtype``
(numpy dtype string), ``shape`` (list of ints), ``params`` (whatever
the codec's encode returned — everything decode needs), ``crc32``
(8-hex crc of the payload bytes), ``raw_nbytes`` (decoded size, the
bytes-on-disk accounting numerator).

The crc is **embedded**, so compressed tiles carry their own
integrity stamp: no ``.crc`` sidecar, no crash window between payload
and stamp, and :func:`verify_tile_blob` classifies a file as
``ok`` / ``torn`` / ``corrupt`` from its bytes alone — exactly the
ladder vocabulary :mod:`tpudas.integrity.audit` speaks.

Every encode/decode is traced (``codec.encode`` / ``codec.decode``
spans) and accounted (``tpudas_codec_*`` metrics) so compression
ratios and codec wall time are first-class observables — the PR-11
bench reads the byte counters for its savings figures.
"""

from __future__ import annotations

import json
import struct
import time

import numpy as np

from tpudas.codec.codecs import CodecError, get_codec
from tpudas.integrity.checksum import crc32_hex
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span

__all__ = [
    "MAGIC",
    "TILE_BLOB_SUFFIX",
    "FRAME_VERSION",
    "decode_tile",
    "encode_tile",
    "read_tile_header",
    "verify_tile_blob",
]

MAGIC = b"TPTC"
FRAME_VERSION = 1
# compressed tiles live beside legacy raw tiles as
# ``L<level>/<idx>.tpt`` — distinct suffix, so a mixed store is
# unambiguous file by file
TILE_BLOB_SUFFIX = ".tpt"

_LEN = struct.Struct("<I")


def encode_tile(arr, codec_id: str, **params) -> bytes:
    """One tile array -> one self-describing compressed blob."""
    codec = get_codec(codec_id)
    arr = np.ascontiguousarray(arr)
    reg = get_registry()
    t0 = time.perf_counter()
    with span("codec.encode", codec=codec.id):
        payload, params_out = codec.encode(arr, **params)
    header = {
        "version": FRAME_VERSION,
        "codec": codec.id,
        "dtype": arr.dtype.str,
        "shape": [int(s) for s in arr.shape],
        "params": params_out,
        "crc32": crc32_hex(payload),
        "raw_nbytes": int(arr.nbytes),
    }
    hdr = json.dumps(header, sort_keys=True,
                     separators=(",", ":")).encode()
    blob = MAGIC + _LEN.pack(len(hdr)) + hdr + payload
    reg.counter(
        "tpudas_codec_tiles_encoded_total",
        "tiles encoded into the compressed container",
        labelnames=("codec",),
    ).inc(codec=codec.id)
    reg.counter(
        "tpudas_codec_raw_bytes_total",
        "uncompressed tile bytes fed into codec encodes",
        labelnames=("codec",),
    ).inc(float(arr.nbytes), codec=codec.id)
    reg.counter(
        "tpudas_codec_encoded_bytes_total",
        "compressed tile bytes produced by codec encodes "
        "(header included)",
        labelnames=("codec",),
    ).inc(float(len(blob)), codec=codec.id)
    reg.histogram(
        "tpudas_codec_encode_seconds",
        "wall time of one tile encode",
        labelnames=("codec",),
    ).observe(time.perf_counter() - t0, codec=codec.id)
    return blob


def _split(blob: bytes) -> tuple:
    """``(header_dict, payload_bytes)`` of one blob; CodecError on
    anything that does not parse (bad magic, truncated header)."""
    if blob[:4] != MAGIC:
        raise CodecError(
            f"not a tpudas tile blob (magic {blob[:4]!r})"
        )
    if len(blob) < 8:
        raise CodecError("truncated tile blob (no header length)")
    (hlen,) = _LEN.unpack(blob[4:8])
    hdr_bytes = blob[8 : 8 + hlen]
    if len(hdr_bytes) != hlen:
        raise CodecError("truncated tile blob (torn header)")
    try:
        header = json.loads(hdr_bytes)
    except ValueError as exc:
        raise CodecError(f"unparseable tile header: {exc}") from exc
    if not isinstance(header, dict) or header.get("version") != (
        FRAME_VERSION
    ):
        raise CodecError(
            f"unknown tile frame version "
            f"{header.get('version') if isinstance(header, dict) else header!r}"
        )
    return header, blob[8 + hlen :]


def read_tile_header(blob: bytes) -> dict:
    """The parsed header of one blob (payload untouched)."""
    return _split(blob)[0]


def verify_tile_blob(blob: bytes) -> str:
    """``"ok"`` | ``"torn"`` (payload crc mismatch — a torn write or
    bit rot behind an intact header) | ``"corrupt"`` (the header
    itself does not parse).  The audit's classification primitive for
    compressed tiles — the embedded-crc analogue of
    :func:`tpudas.integrity.checksum.verify_file_checksum`."""
    try:
        header, payload = _split(blob)
        stamp = header["crc32"]
    except (CodecError, KeyError, TypeError):
        return "corrupt"
    return "ok" if crc32_hex(payload) == stamp else "torn"


def decode_tile(blob: bytes, verify: bool = True) -> np.ndarray:
    """One blob -> the tile array.  ``verify=True`` (default) checks
    the embedded payload crc first and raises :class:`CodecError` on
    mismatch — the read path's integrity gate."""
    header, payload = _split(blob)
    if verify and crc32_hex(payload) != header.get("crc32"):
        get_registry().counter(
            "tpudas_codec_verify_failures_total",
            "tile blobs rejected for an embedded-crc mismatch",
        ).inc()
        raise CodecError(
            "tile payload failed its embedded crc32 check "
            "(torn write or bit rot)"
        )
    codec = get_codec(header.get("codec"))
    reg = get_registry()
    t0 = time.perf_counter()
    with span("codec.decode", codec=codec.id):
        arr = codec.decode(
            payload,
            header.get("dtype"),
            tuple(header.get("shape", ())),
            header.get("params") or {},
        )
    if list(arr.shape) != list(header.get("shape", ())):
        raise CodecError(
            f"decode produced shape {arr.shape}, header declares "
            f"{header.get('shape')}"
        )
    reg.counter(
        "tpudas_codec_tiles_decoded_total",
        "tiles decoded from the compressed container",
        labelnames=("codec",),
    ).inc(codec=codec.id)
    reg.histogram(
        "tpudas_codec_decode_seconds",
        "wall time of one tile decode",
        labelnames=("codec",),
    ).observe(time.perf_counter() - t0, codec=codec.id)
    return arr
