"""tpudas.codec — the compressed tile codec (ISSUE 11).

The serve-side tile pyramid (:mod:`tpudas.serve.tiles`) historically
stored every completed tile as a raw ``.npy`` + ``.crc`` sidecar.
DAS data compresses extremely well under the right transform —
DASPack (PAPERS.md) demonstrates controlled lossless/lossy DAS
compression at high ratios — and a fleet of stores multiplies the
bytes.  This package is the codec layer the whole serve stack rides:

- :mod:`tpudas.codec.frame` — a versioned, **self-describing** tile
  container: one small JSON header (codec id, dtype, shape, params,
  payload crc32, raw byte count) followed by the encoded payload.
  The crc32 is embedded, so compressed tiles need no ``.crc``
  sidecar and a torn write is detected from the file alone
  (:func:`verify_tile_blob` is what the integrity audit calls).
- :mod:`tpudas.codec.codecs` — the pluggable codec registry.  Ships
  a lossless ``deflate``, a lossless ``bitshuffle-deflate`` (bit
  transposition so slowly-varying float fields deflate far better),
  and a controlled-lossy ``quantize-deflate`` whose ``max_error``
  parameter is an absolute error *bound*, DASPack's contract —
  quantize to an integer grid sized so the bound holds, then the
  lossless pipeline.  All three are NaN-gap-safe: lossless codecs
  are byte-exact by construction, the lossy codec carries NaNs
  through a reserved integer sentinel so gap masks survive exactly.

Codec selection is a **spec string** (``"bitshuffle-deflate"``,
``"quantize-deflate:max_error=1e-3"``) accepted by the pyramid
writer (``sync_pyramid(codec=...)`` / ``TPUDAS_CODEC=``) and by
``rebuild_pyramid`` for offline re-encodes.  See SERVING.md
("Compressed tile codec") for the on-disk format and the CDN story
it unlocks.
"""

from tpudas.codec.codecs import (
    Codec,
    CodecError,
    codec_ids,
    get_codec,
    parse_codec_spec,
    register_codec,
)
from tpudas.codec.frame import (
    MAGIC,
    TILE_BLOB_SUFFIX,
    decode_tile,
    encode_tile,
    read_tile_header,
    verify_tile_blob,
)

__all__ = [
    "Codec",
    "CodecError",
    "MAGIC",
    "TILE_BLOB_SUFFIX",
    "codec_ids",
    "decode_tile",
    "encode_tile",
    "get_codec",
    "parse_codec_spec",
    "read_tile_header",
    "register_codec",
    "verify_tile_blob",
]
