"""The pluggable codec registry and the shipped codecs.

A codec transforms one tile array into a compressed payload and back:

- ``encode(arr, **params) -> (payload_bytes, params_out)`` — the
  returned ``params_out`` is everything ``decode`` needs and is
  persisted verbatim in the tile header (so a blob decodes with no
  out-of-band state);
- ``decode(payload, dtype, shape, params) -> np.ndarray`` — must
  reproduce the array byte-exactly for ``lossless=True`` codecs, and
  within ``params["max_error"]`` absolutely (NaN positions exact)
  otherwise.

Both directions must be **deterministic**: the crash-only tile store
re-encodes a crashed append's rows and relies on the retry producing
the same bytes, and the crash drill asserts pyramid trees
byte-identical between a killed run and an uninterrupted control.
That is why the deflate level is pinned in ``params_out`` instead of
left to a library default that could drift.

Shipped codecs
--------------

``deflate``
    zlib over the raw array bytes.  The baseline: byte-exact, cheap,
    modest ratios on float noise.

``bitshuffle-deflate``
    Bit transposition (all elements' bit 0, then all bit 1, ...)
    before deflate — the Blosc/HDF5 *bitshuffle* transform,
    implemented here in pure numpy (``unpackbits`` / transpose /
    ``packbits``) so nothing new is vendored.  Slowly-varying fields
    (decimated DAS output, quantized integers) share high bits across
    neighbours, so the transposed stream is long runs the deflate
    stage collapses.  Byte-exact.

``quantize-deflate``
    Controlled-lossy: values are rounded to a uniform grid of step
    ``max_error`` (absolute), giving a reconstruction error of at
    most ``max_error / 2`` before output-dtype rounding — comfortably
    inside the advertised ``max_error`` bound for any error bound the
    output dtype can express at the data's magnitude.  The integer
    grid indices are stored through the lossless bitshuffle+deflate
    pipeline in the narrowest integer width that fits; NaN rows (the
    pyramid's data-gap honesty) map to the width's reserved minimum
    sentinel and come back as exactly NaN.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Callable

import numpy as np

__all__ = [
    "Codec",
    "CodecError",
    "codec_ids",
    "get_codec",
    "parse_codec_spec",
    "register_codec",
]

_DEFAULT_DEFLATE_LEVEL = 6


class CodecError(RuntimeError):
    """A tile blob that cannot be trusted: bad magic, torn header,
    payload crc mismatch, unknown codec id, or a decode that does not
    reproduce the declared geometry.  Readers treat this exactly like
    a failed ``.crc`` sidecar check — fall down the degradation
    ladder, never serve the bytes."""


@dataclass(frozen=True)
class Codec:
    """One registered codec: an id, a losslessness contract, and the
    encode/decode pair.  Frozen so registry entries cannot be mutated
    out from under stores that recorded the id in their manifest.

    ``condition`` (lossy codecs only) maps incoming rows onto the
    codec's representable set — e.g. the quantization grid — such
    that ``decode(encode(condition(x))) == condition(x)`` exactly.
    The tile store applies it to rows *before* they reach tails or
    tiles, which is what keeps the incremental pyramid build
    byte-identical to an offline rebuild under a lossy codec: every
    value on disk is already representable, so where an append's
    chunk boundaries fall can never change what a tile encodes."""

    id: str
    lossless: bool
    encode: Callable  # (arr, **params) -> (payload: bytes, params_out)
    decode: Callable  # (payload, dtype, shape, params) -> np.ndarray
    condition: Callable | None = None  # (arr, **params) -> arr


_REGISTRY: dict = {}


def register_codec(codec: Codec) -> Codec:
    """Add (or replace) one codec in the process-wide registry.  The
    id must be lowercase ``[a-z0-9-]`` — it is embedded in tile
    headers and codec spec strings."""
    cid = str(codec.id)
    if not cid or not all(c.isalnum() or c == "-" for c in cid) or (
        cid != cid.lower()
    ):
        raise ValueError(
            f"codec id {cid!r} must be lowercase alphanumeric/dashes"
        )
    _REGISTRY[cid] = codec
    return codec


def get_codec(codec_id: str) -> Codec:
    codec = _REGISTRY.get(str(codec_id))
    if codec is None:
        raise CodecError(
            f"unknown codec id {codec_id!r}; registered: "
            f"{sorted(_REGISTRY)}"
        )
    return codec


def codec_ids() -> tuple:
    """Every registered codec id, sorted — the lint surface
    ``tools/check_codecs.py`` asserts the test matrix covers."""
    return tuple(sorted(_REGISTRY))


def parse_codec_spec(spec) -> tuple:
    """``(codec_id, params)`` from a codec spec string.

    Grammar: ``<id>[:k=v[,k=v...]]`` — e.g. ``"bitshuffle-deflate"``,
    ``"quantize-deflate:max_error=1e-3,level=9"``.  ``None``, ``""``,
    ``"raw"``, ``"none"`` and ``"0"`` all mean *no codec* (the legacy
    raw-``.npy`` store) and return ``(None, {})``.  Values parse as
    int, then float, then stay strings.  The id must be registered.
    """
    if spec is None:
        return None, {}
    s = str(spec).strip()
    if s.lower() in ("", "raw", "none", "0"):
        return None, {}
    cid, _, tail = s.partition(":")
    cid = cid.strip()
    get_codec(cid)  # unknown id fails loudly at config time
    params: dict = {}
    if tail.strip():
        for item in tail.split(","):
            k, sep, v = item.partition("=")
            if not sep or not k.strip():
                raise ValueError(
                    f"bad codec spec item {item!r} in {spec!r} "
                    "(want k=v)"
                )
            v = v.strip()
            try:
                params[k.strip()] = int(v)
            except ValueError:
                try:
                    params[k.strip()] = float(v)
                except ValueError:
                    params[k.strip()] = v
    return cid, params


# ---------------------------------------------------------------------------
# the bitshuffle transform (pure numpy)

def bitshuffle(data: bytes, itemsize: int) -> bytes:
    """Transpose ``data`` (a whole number of ``itemsize``-byte
    elements) to bit-plane order: all elements' bit 0 first, then all
    bit 1, ...  Exactly reversible by :func:`bitunshuffle` given the
    element count (the tile header carries the shape)."""
    if itemsize <= 0 or len(data) % itemsize:
        raise CodecError(
            f"bitshuffle: {len(data)} bytes is not a whole number of "
            f"{itemsize}-byte elements"
        )
    if not data:
        return b""
    a = np.frombuffer(data, np.uint8).reshape(-1, itemsize)
    bits = np.unpackbits(a, axis=1)  # (n, 8*itemsize), bit-endian rows
    # row-major flatten of the (8*itemsize, n) transpose: total bit
    # count is n*itemsize*8, so packbits pads nothing and the decode
    # side's count-bounded unpack reshapes it back exactly
    return np.packbits(np.ascontiguousarray(bits.T)).tobytes()


def bitunshuffle(data: bytes, itemsize: int, n_elems: int) -> bytes:
    """Inverse of :func:`bitshuffle` for ``n_elems`` elements."""
    if n_elems == 0:
        return b""
    total_bits = 8 * itemsize * n_elems
    if len(data) * 8 < total_bits:
        raise CodecError(
            f"bitunshuffle: {len(data)} bytes cannot hold "
            f"{n_elems} x {itemsize}-byte elements"
        )
    bits = np.unpackbits(
        np.frombuffer(data, np.uint8), count=total_bits
    ).reshape(8 * itemsize, n_elems)
    return np.packbits(
        np.ascontiguousarray(bits.T), axis=1
    ).tobytes()


# ---------------------------------------------------------------------------
# lossless codecs

def _deflate_encode(arr: np.ndarray, level=None, **_ignored):
    level = int(_DEFAULT_DEFLATE_LEVEL if level is None else level)
    payload = zlib.compress(
        np.ascontiguousarray(arr).tobytes(), level
    )
    return payload, {"level": level}


def _deflate_decode(payload: bytes, dtype, shape, params):
    raw = zlib.decompress(payload)
    return _from_bytes(raw, dtype, shape)


def _bitshuffle_encode(arr: np.ndarray, level=None, **_ignored):
    level = int(_DEFAULT_DEFLATE_LEVEL if level is None else level)
    arr = np.ascontiguousarray(arr)
    shuffled = bitshuffle(arr.tobytes(), arr.dtype.itemsize)
    return zlib.compress(shuffled, level), {"level": level}


def _bitshuffle_decode(payload: bytes, dtype, shape, params):
    dtype = np.dtype(dtype)
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    raw = bitunshuffle(zlib.decompress(payload), dtype.itemsize, n)
    return _from_bytes(raw, dtype, shape)


def _from_bytes(raw: bytes, dtype, shape) -> np.ndarray:
    dtype = np.dtype(dtype)
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if len(raw) != n * dtype.itemsize:
        raise CodecError(
            f"decoded payload is {len(raw)} bytes, tile header "
            f"declares {n} x {dtype} = {n * dtype.itemsize}"
        )
    return np.frombuffer(raw, dtype).reshape(shape).copy()


# ---------------------------------------------------------------------------
# controlled-lossy quantization

_QUANT_WIDTHS = (np.int8, np.int16, np.int32, np.int64)
_DEFAULT_MAX_ERROR = 1e-3


def _quantize_encode(arr: np.ndarray, max_error=None, level=None,
                     **_ignored):
    """Round to a uniform grid of step ``max_error`` (reconstruction
    error <= max_error/2, half the advertised bound — the headroom
    absorbs output-dtype rounding), sentinel-encode NaNs, store the
    indices through bitshuffle+deflate in the narrowest width that
    fits."""
    max_error = float(
        _DEFAULT_MAX_ERROR if max_error is None else max_error
    )
    if not (max_error > 0) or not np.isfinite(max_error):
        raise ValueError(
            f"quantize-deflate needs a positive finite max_error, "
            f"got {max_error!r}"
        )
    arr = np.ascontiguousarray(arr)
    if not np.issubdtype(arr.dtype, np.floating):
        raise CodecError(
            "quantize-deflate only encodes floating tiles; use a "
            f"lossless codec for dtype {arr.dtype}"
        )
    level = int(_DEFAULT_DEFLATE_LEVEL if level is None else level)
    step = max_error
    x = arr.astype(np.float64, copy=False)
    finite = np.isfinite(x)
    _check_grid_resolvable(arr, x, finite, step)
    # non-finite rows stay 0 here; the width's sentinel replaces them
    # after the cast below
    q = np.zeros(x.shape, np.float64)
    np.round(np.divide(x, step, where=finite, out=q), out=q)
    for width in _QUANT_WIDTHS:
        info = np.iinfo(width)
        # min is the NaN sentinel, so real indices must fit strictly
        # inside (min, max]
        if q.size == 0 or (
            finite.any()
            and q[finite].min() > info.min
            and q[finite].max() <= info.max
        ) or not finite.any():
            qi = q.astype(width)
            qi[~finite] = info.min
            break
    else:
        raise CodecError(
            "quantize-deflate: grid indices overflow int64 — "
            f"max_error {max_error} is too fine for this data range"
        )
    shuffled = bitshuffle(qi.tobytes(), qi.dtype.itemsize)
    payload = zlib.compress(shuffled, level)
    return payload, {
        "max_error": max_error,
        "step": step,
        "itype": qi.dtype.name,
        "level": level,
    }


def _quantize_decode(payload: bytes, dtype, shape, params):
    try:
        itype = np.dtype(params["itype"])
        step = float(params["step"])
    except (KeyError, TypeError) as exc:
        raise CodecError(
            f"quantize-deflate header is missing {exc} — blob "
            "predates this reader or is corrupt"
        ) from exc
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    raw = bitunshuffle(zlib.decompress(payload), itype.itemsize, n)
    if len(raw) != n * itype.itemsize:
        raise CodecError(
            f"quantize-deflate payload is {len(raw)} bytes, header "
            f"declares {n} x {itype}"
        )
    qi = np.frombuffer(raw, itype).reshape(shape)
    out = qi.astype(np.float64) * step
    out[qi == np.iinfo(itype).min] = np.nan
    return out.astype(np.dtype(dtype), copy=False)


register_codec(Codec(
    id="deflate", lossless=True,
    encode=_deflate_encode, decode=_deflate_decode,
))
register_codec(Codec(
    id="bitshuffle-deflate", lossless=True,
    encode=_bitshuffle_encode, decode=_bitshuffle_decode,
))
def _check_grid_resolvable(arr, x64, finite, step) -> None:
    """Refuse a grid finer than the array dtype can hold: below ``4 *
    eps * |x|`` the dtype's own rounding perturbs a value by more
    than half a grid step, so grid indices stop being stable under a
    store/decode roundtrip — the deterministic-rebuild contract (and
    the error bound itself) would silently break.  The remedy is a
    looser ``max_error`` or a lossless codec."""
    if not finite.any():
        return
    eps = np.finfo(np.asarray(arr).dtype).eps
    amax = float(np.max(np.abs(x64[finite])))
    if amax and step < 4.0 * eps * amax:
        raise CodecError(
            f"quantize-deflate max_error {step:g} is below the "
            f"{np.asarray(arr).dtype} resolution at this data's "
            f"magnitude (|x| up to {amax:g}); loosen max_error or "
            "use a lossless codec"
        )


def _quantize_condition(arr, max_error=None, **_ignored):
    """Snap values onto the quantization grid (NaN passes through).
    Computes exactly what decode-of-encode computes — ``round(x /
    step) * step`` in float64, cast back — so conditioned rows
    roundtrip the codec bit-exactly."""
    max_error = float(
        _DEFAULT_MAX_ERROR if max_error is None else max_error
    )
    if not (max_error > 0) or not np.isfinite(max_error):
        raise ValueError(
            f"quantize-deflate needs a positive finite max_error, "
            f"got {max_error!r}"
        )
    arr = np.asarray(arr)
    step = max_error
    x = arr.astype(np.float64)
    with np.errstate(invalid="ignore"):
        finite = np.isfinite(x)
        _check_grid_resolvable(arr, x, finite, step)
        out = np.round(x / step) * step
        # every non-finite value (inf included) becomes NaN — the
        # SAME mapping encode's sentinel applies, so the roundtrip
        # contract decode(encode(condition(x))) == condition(x) holds
        # for inf inputs too (an inf that conditioned to inf would
        # decode to NaN and break tails-vs-tile byte identity)
        out[~finite] = np.nan
    return out.astype(arr.dtype, copy=False)


register_codec(Codec(
    id="quantize-deflate", lossless=False,
    encode=_quantize_encode, decode=_quantize_decode,
    condition=_quantize_condition,
))
