"""Pallas TPU kernel: strided (decimating) FIR along the time axis.

This is the hot inner loop of the cascade engine (tpudas.ops.fir): for
a (T, C) block and frame-blocked taps ``hb`` (B, R),

    y[k, c] = sum_{b, r} hb[b, r] * x[(k + b) * R + r, c]

i.e. a causal FIR of length <= B*R evaluated only at stride-R output
positions — the op the reference executes as full-rate ``sosfiltfilt``
+ decimating ``interpolate`` (lf_das.py:223-225) and XLA executes as
B shifted matmuls with B full HBM passes.

Design (v2, informed by on-chip measurement — see PERF.md §4):

- **MXU banded matmul, not VPU shifted adds.**  For an SB-frame output
  sub-block the FIR is one dot ``Y = A @ X`` with
  ``A[k, k*R + j] = h[j]`` the (SB, (SB+HALO)*R) banded tap matrix and
  ``X`` the flat 2-D view of the input rows.  A is ~96% zeros, but the
  MXU has ~50x the VPU's throughput: the VPU formulation measured
  compute-bound at 174 GB/s while this one is bound by the DMA stream.
  A rides along as a grid-constant input (index map (0,0)): the
  pipeline fetches it once and skips the re-DMA on later steps.
- **P parallel input streams.**  A single auto-pipelined input block
  measured ~185 GB/s regardless of block geometry (one DMA in flight
  can't cover HBM latency).  Each grid step therefore reads P separate
  main blocks — P views of the same array at consecutive block
  indices, each with its own double buffer and in-flight DMA.
- **f32 accuracy via a 3-pass bf16 split** (hi/lo split of both
  operands, dropping lo*lo): Mosaic lowers only DEFAULT (1-pass bf16,
  ~3e-3 abs error on unit-scale data — too coarse) and HIGHEST
  (6-pass); 3 passes give ~1e-5 at half HIGHEST's MXU cost.  Interpret
  mode (the CPU test path) uses exact f32 dots instead, so CPU
  equality tests see the mathematically exact kernel.

Layout: the halo of main block j is the head of main block j+1 — for
j < P-1 that block is already resident in the same grid step, so only
the LAST sub-block needs a dedicated halo input (the head of the next
step's first main block, expressed as a second BlockSpec over the same
array; possible because HALO_F divides SB, so the halo offset is an
integer block index).

VMEM at (P, SB, CB) = (4, 128, 128), R=8: 4 mains x 512 KB x 2
(double-buffered) + A 557 KB + out 256 KB x 2 + halo 32 KB x 2 — about
6 MB of the ~16 MB budget.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "fir_decimate_pallas",
    "stage_input_rows",
    "fused_cascade_pallas",
    "fused_taps_fit",
    "kernel_quantum",
    "channel_block",
    "pallas_p",
]

_SB = 128  # output frames per sub-block (one MXU dot)


def _env_geom(name: str, default: int, multiple_of: int = 1) -> int:
    """Env-tunable geometry knob: empty/unset -> default; anything
    else must be a positive int (and a lane multiple where required)
    — fail at import with the variable named, not mid-run."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val < 1 or val % multiple_of:
        raise ValueError(
            f"{name} must be a positive multiple of {multiple_of}, "
            f"got {val}"
        )
    return val


# geometry is env-tunable so on-chip sweeps need no code edits; the
# engine's chain layout calls the same accessors, keeping the sizing
# math and the kernel grid in lockstep.  Read at CALL time (not
# import) so a retune (tools/retune_stage_ok.py) applies mid-process:
# every jit/layout cache that depends on these carries
# ``tpudas.ops.fir.knob_fingerprint()`` in its key, so a changed env
# value dispatches fresh instead of hitting a stale compile.


def pallas_p() -> int:
    """Parallel DMA streams per grid step (``TPUDAS_PALLAS_P``)."""
    return _env_geom("TPUDAS_PALLAS_P", 4)


def kernel_quantum() -> int:
    """Output frames per grid step (the grid quantum): ``_SB`` frames
    per parallel sub-block times :func:`pallas_p` sub-blocks."""
    return _SB * pallas_p()


def channel_block() -> int:
    """Channel (lane) block size (``TPUDAS_PALLAS_CB``)."""
    return _env_geom("TPUDAS_PALLAS_CB", 128, multiple_of=128)


def _mosaic_knobs():
    """Experimental Mosaic/pipeline knobs for on-chip sweeps (read at
    call time so one process can A/B them without reimport):

    - TPUDAS_PALLAS_DIMSEM: dimension_semantics for the (k, c) grid —
      "parallel", "arbitrary", or a comma pair like
      "arbitrary,parallel" (order follows the ACTIVE grid order).
    - TPUDAS_PALLAS_GRID: "kc" (default; channel block varies fastest)
      or "ck" (output-frame block varies fastest, so consecutive grid
      steps walk sequential rows of the input).
    - TPUDAS_PALLAS_VMEM_MB: vmem_limit_bytes override, in MiB —
      larger double-buffering headroom for big-block geometries.

    Defaults leave everything unset: identical behavior/lowering to
    the kernel that passed chip_check (chip_r05/chip_check.log).
    """
    sems_env = os.environ.get("TPUDAS_PALLAS_DIMSEM", "").strip()
    grid_order = os.environ.get("TPUDAS_PALLAS_GRID", "kc").strip() or "kc"
    if grid_order not in ("kc", "ck"):
        raise ValueError(
            f"TPUDAS_PALLAS_GRID must be 'kc' or 'ck', got {grid_order!r}"
        )
    vmem_mb = _env_geom("TPUDAS_PALLAS_VMEM_MB", 0)  # 0 = unset
    cp_kwargs = {}
    if sems_env:
        sems = tuple(s.strip() for s in sems_env.split(","))
        if len(sems) == 1:
            sems = sems * 2
        if len(sems) != 2 or not all(
            s in ("parallel", "arbitrary") for s in sems
        ):
            raise ValueError(
                "TPUDAS_PALLAS_DIMSEM must be 'parallel', 'arbitrary' "
                f"or a comma pair of those, got {sems_env!r}"
            )
        cp_kwargs["dimension_semantics"] = sems
    if vmem_mb:
        cp_kwargs["vmem_limit_bytes"] = vmem_mb * 2**20
    call_kwargs = {}
    if cp_kwargs:
        # renamed TPUCompilerParams -> CompilerParams across jax
        # versions; accept either spelling
        params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        call_kwargs["compiler_params"] = params_cls(**cp_kwargs)
    return grid_order, call_kwargs


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _halo_frames(B: int, sb: int = _SB) -> int:
    """Halo frames: B rounded up to a sublane multiple that also
    divides the sub-block (so the halo offset is an integer block
    index). Single source for both the kernel and the sizing math."""
    halo_f = _round_up(B, 8)
    while halo_f <= sb and sb % halo_f != 0:
        halo_f += 8
    return halo_f


def stage_input_rows(B: int, R: int, n_out: int, kb: int | None = None) -> int:
    """Input rows this kernel consumes to emit ``n_out`` outputs with
    B tap-frames at stride R — the grid/halo-padded figure. Feeding
    exactly this many rows makes the kernel pad-free (the internal
    ``jnp.pad`` otherwise materializes a full copy of the input, which
    at engine scale is an extra HBM round-trip per stage)."""
    kb = kernel_quantum() if kb is None else int(kb)
    sb = min(int(kb), _SB)
    return (_round_up(int(n_out), kb) + _halo_frames(B, sb)) * R


def _split_bf16(v):
    hi = v.astype(jnp.bfloat16)
    lo = (v - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _dot_3x(a, x):
    """~f32-accurate matmul from 3 bf16 MXU passes (drops lo*lo)."""
    a_hi, a_lo = _split_bf16(a)
    x_hi, x_lo = _split_bf16(x)
    d = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    return d(a_hi, x_hi) + d(a_hi, x_lo) + d(a_lo, x_hi)


def _dot_f32(a, x):
    return jnp.dot(
        a,
        x,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _kernel_body(P, SB, CB, halo_rows, exact):
    dot = _dot_f32 if exact else _dot_3x

    def kernel(*refs):
        a_ref = refs[0]
        mains = refs[1 : 1 + P]
        halo_ref = refs[1 + P]
        out_ref = refs[2 + P]
        for j in range(P):
            head = (
                mains[j + 1][:halo_rows]
                if j < P - 1
                else halo_ref[:]
            )
            x = jnp.concatenate([mains[j][:], head], axis=0)
            # int16 ingest: bare cast in VMEM after the (half-width)
            # DMA — the quantization scale is the caller's (applied to
            # the decimated output; the FIR is linear).  Exact under
            # the 3x split too: a 16-bit integer is hi+lo bf16 exactly.
            x = x.astype(jnp.float32)
            out_ref[j * SB : (j + 1) * SB] = dot(a_ref[:], x)

    return kernel


@functools.lru_cache(maxsize=64)
def _band_matrix(taps: tuple, R: int, SB: int, rows: int) -> np.ndarray:
    h = np.asarray(taps, np.float32)
    A = np.zeros((SB, rows), np.float32)
    for k in range(SB):
        A[k, k * R : k * R + len(h)] = h
    return A


# ---------------------------------------------------------------------------
# v1 implementation (VPU shifted multiply-reduce): the kernel behind the
# proven 29.06 G ch-samp/s on-chip record (PERF.md §3).  Kept selectable
# via TPUDAS_PALLAS_IMPL=v1 — and as the bench's automatic middle
# fallback — until the v2 MXU kernel has been validated by Mosaic on
# real hardware (it has only interpret-mode coverage; PERF.md §5).


def _kernel_body_v1(B, KB, CB):
    def kernel(hb_ref, xm_ref, xh_ref, out_ref):
        full = jnp.concatenate(
            [xm_ref[:], xh_ref[:]], axis=0
        ).astype(jnp.float32)
        acc = jnp.zeros((KB, CB), jnp.float32)
        for b in range(B):
            acc = acc + jnp.sum(
                full[b : b + KB] * hb_ref[b][None, :, None], axis=1
            )
        out_ref[:] = acc

    return kernel


def _fir_decimate_pallas_v1(x, hb, R: int, n_out: int,
                            interpret: bool = False):
    """The round-4 session-1 kernel: 128-frame blocks, taps as a VMEM
    operand, B shifted VPU multiply-reduces.  Accepts int16 input
    (bare cast, like v2).  Tolerates inputs sized for the v2 grid
    quantum (it slices its own, smaller need)."""
    KB = CB = 128
    hb = np.asarray(hb)
    B = int(hb.shape[0])
    T, C = x.shape
    halo_f = _halo_frames(B, KB)
    if halo_f > KB:
        raise ValueError(
            f"tap frames ({B}) exceed the kernel block ({KB} frames); "
            "use the XLA polyphase path for very long stages"
        )
    nk = -(-int(n_out) // KB)
    nc = -(-int(C) // CB)
    Kpad = nk * KB
    need_rows = (Kpad + halo_f) * R
    pad_t = need_rows - T
    pad_c = nc * CB - C
    if pad_t > 0 or pad_c > 0:
        x = jnp.pad(x, ((0, max(pad_t, 0)), (0, pad_c)))
    xr = x[:need_rows].reshape(Kpad + halo_f, R, nc * CB)
    hb_pad = np.zeros((halo_f, R), np.float32)
    hb_pad[:B] = hb.astype(np.float32)
    step = KB // halo_f
    out = pl.pallas_call(
        _kernel_body_v1(B, KB, CB),
        grid=(nk, nc),
        in_specs=[
            pl.BlockSpec(
                (halo_f, R), lambda k, c: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (KB, R, CB),
                lambda k, c: (k, 0, c),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (halo_f, R, CB),
                lambda k, c, _s=step: (k * _s + _s, 0, c),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (KB, CB), lambda k, c: (k, c), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Kpad, nc * CB), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(hb_pad), xr, xr)
    return out[:n_out, :C]


def fir_decimate_pallas(
    x, hb, R: int, n_out: int, interpret: bool = False, kb=None, cb=None
):
    """Strided FIR: x (T, C) f32 or int16, hb (B, R) f32 -> (n_out, C)
    f32.

    ``hb`` must be CONCRETE (host numpy or a settled device array, not
    a tracer): the banded tap matrix is built on the host.  ``x`` may
    be traced — callers jit the enclosing cascade.  ``n_out`` is
    static; the input is zero-padded on the right as needed (outputs
    whose receptive field crosses the pad carry edge artifacts,
    trimmed by the overlap-save caller), and channel counts that are
    not multiples of the lane tile get whole-block zero padding.
    ``kb`` is the grid quantum in output frames (P parallel sub-blocks
    of min(kb, 128) frames each); ``cb`` the channel block.

    int16 ``x`` (the tdas quantized-ingest payload) is cast to f32 in
    VMEM after the half-width DMA and filtered RAW — the caller owns
    the quantization scale and, the FIR being linear, applies it to
    this stage's (decimated, so R-times smaller) output.  Keeping the
    scale out of the kernel keeps it a traced value: one compiled
    executable serves every scale.

    ``TPUDAS_PALLAS_IMPL=v1`` selects the previous VPU formulation
    (the proven-on-hardware kernel; see the v1 section below).
    """
    if os.environ.get("TPUDAS_PALLAS_IMPL", "v2") == "v1":
        return _fir_decimate_pallas_v1(x, hb, R, n_out, interpret)
    B = int(hb.shape[0])
    T, C = x.shape
    KB = kernel_quantum() if kb is None else int(kb)
    CB = channel_block() if cb is None else int(cb)
    SB = min(KB, _SB)
    P = KB // SB
    if KB % SB:
        raise ValueError(f"kb ({KB}) must be a multiple of {SB}")
    halo_f = _halo_frames(B, SB)
    if halo_f > SB:
        raise ValueError(
            f"tap frames ({B}) exceed the kernel sub-block ({SB} "
            "frames); use the XLA polyphase path for very long stages"
        )

    nk = -(-int(n_out) // KB)
    nc = -(-int(C) // CB)
    Kpad = nk * KB
    need_rows = stage_input_rows(B, R, n_out, KB)
    pad_t = need_rows - T
    pad_c = nc * CB - C
    if pad_t > 0 or pad_c > 0:
        x = jnp.pad(x, ((0, max(pad_t, 0)), (0, pad_c)))
    x2 = x[:need_rows]

    # frame-blocked taps (B, R) flatten back to the padded tap vector
    taps = tuple(np.asarray(jax.device_get(hb), np.float32).reshape(-1))
    band_rows = (SB + halo_f) * R
    A = jnp.asarray(_band_matrix(taps, R, SB, band_rows))

    halo_rows = halo_f * R
    step = SB * P // halo_f  # halo offset in halo-block units

    grid_order, call_kwargs = _mosaic_knobs()
    if grid_order == "ck":
        # grid (nc, nk): index-map args arrive as (c, k) — remap to
        # the (k, c) the block coordinates are written in
        grid = (nc, nk)

        def _km(f):
            return lambda c, k, _f=f: _f(k, c)

    else:
        grid = (nk, nc)

        def _km(f):
            return f

    main_specs = [
        pl.BlockSpec(
            (SB * R, CB),
            _km(lambda k, c, j=j: (k * P + j, c)),
            memory_space=pltpu.VMEM,
        )
        for j in range(P)
    ]
    halo_spec = pl.BlockSpec(
        (halo_rows, CB),
        _km(lambda k, c, _s=step: (k * _s + _s, c)),
        memory_space=pltpu.VMEM,
    )
    out = pl.pallas_call(
        _kernel_body(P, SB, CB, halo_rows, exact=interpret),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (SB, band_rows),
                _km(lambda k, c: (0, 0)),
                memory_space=pltpu.VMEM,
            ),
            *main_specs,
            halo_spec,
        ],
        out_specs=pl.BlockSpec(
            (KB, CB), _km(lambda k, c: (k, c)), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Kpad, nc * CB), jnp.float32),
        interpret=interpret,
        **call_kwargs,
    )(A, *([x2] * P), x2)
    return out[:n_out, :C]


# ---------------------------------------------------------------------------
# v3: the FUSED cascade kernel (ISSUE 10).  One pallas_call runs the
# whole multistage decimator: the grid walks (channel block, time
# chunk); each grid step reads one full-rate input chunk, pushes it
# through EVERY stage back to back inside VMEM, and writes only the
# final decimated output chunk.  Each stage's trailing-sample state
# lives in a VMEM scratch buffer that persists across the time-chunk
# grid steps (initialized from the carry refs at t == 0, flushed to
# the carry outputs every step so the last step's write is the new
# carry) — zero per-stage full-rate intermediates ever reach HBM.
#
# Stage math is the v1 VPU formulation (exact f32 multiply-reduce, no
# bf16 split): the per-stage work is ~B multiply-adds per input sample
# and the fused kernel's DMA stream is ~R-times lighter than the
# per-stage kernels' (input read once, decimated output only), so the
# VPU-vs-MXU tradeoff of PERF.md §4 tilts back — the v2 MXU banded
# matmul needed its arithmetic headroom to keep up with TWO full-rate
# HBM streams per stage, which the fused kernel has eliminated.
# Like v2 at its introduction, v3 has interpret-mode coverage here and
# awaits Mosaic validation on silicon (PERF.md §5 protocol).
#
# Tail alignment trick: stage i carries p_i trailing input rows
# (tpudas.ops.fir.stream_carry_sizes — p_i is NOT generally a
# multiple of R_i, and the carry layout is shared byte-for-byte with
# the unfused engines).  The scratch holds q_i = round_up(p_i, R_i)
# rows — off_i = q_i - p_i extra OLDER rows whose values multiply
# only against zero-padded taps — so the concatenated (q_i + chunk_i)
# working block frame-blocks exactly into (q_i/R_i + k_i) tap frames
# and the taps shift by off_i into hb'[b*R + r] = h[b*R + r - off_i].


def _round_up_div(a: int, b: int) -> int:
    return -(-int(a) // int(b))


def fused_taps_fit(stages, chunk_out: int) -> bool:
    """Whether :func:`fused_cascade_pallas` can run this plan at this
    chunk size: every stage's chunk must be a whole number of frames
    (guaranteed by construction) and the per-step VMEM footprint —
    input chunk + all stage scratch + taps — must fit the ~16 MiB
    budget with double-buffering headroom."""
    cb = channel_block()
    ratio = 1
    for R, _h in stages:
        ratio *= int(R)
    chunk_in = int(chunk_out) * ratio
    vmem = 2 * chunk_in * cb * 4  # double-buffered input block
    rows = chunk_in
    for R, h in stages:
        p = max(len(h) - int(R), 0)
        q = _round_up_div(p, int(R)) * int(R)
        vmem += (q + rows) * cb * 4  # working block + scratch
        rows //= int(R)
    vmem += 2 * int(chunk_out) * cb * 4  # double-buffered output
    return vmem <= 12 * 2**20


def _fused_stage_meta(stages, sizes, chunk_in: int):
    """Static per-stage geometry for the fused kernel: (R, k, p, q,
    off, L, hbp) with hbp the off-shifted frame-blocked taps and L
    the true tap length (the kernel SLICES the off/pad positions out
    of the partial frames rather than multiplying by zero — 0 * NaN
    would smear NaN outside the receptive field)."""
    meta = []
    rows = int(chunk_in)
    for (R, h), p in zip(stages, sizes):
        R = int(R)
        h = np.asarray(h, np.float32)
        p = int(p)
        q = _round_up_div(p, R) * R
        off = q - p
        k = rows // R
        bp = _round_up_div(off + len(h), R)
        hbp = np.zeros((bp, R), np.float32)
        hbp.reshape(-1)[off : off + len(h)] = h
        meta.append((R, k, p, q, off, int(len(h)), hbp))
        rows = k
    return meta


def _fused_kernel_body(meta, CB):
    n_stage = len(meta)
    n_state = sum(1 for _R, _k, p, _q, _off, _L, _h in meta if p)

    def kernel(*refs):
        taps = refs[:n_stage]
        x_ref = refs[n_stage]
        cin = refs[n_stage + 1 : n_stage + 1 + n_state]
        y_ref = refs[n_stage + 1 + n_state]
        cout = refs[n_stage + 2 + n_state : n_stage + 2 + 2 * n_state]
        scr = refs[n_stage + 2 + 2 * n_state :]
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _init():
            si = 0
            for _R, _k, p, q, off, _L, _h in meta:
                if not p:
                    continue
                if off:
                    scr[si][:off] = jnp.zeros((off, CB), jnp.float32)
                scr[si][off:] = cin[si][:]
                si += 1

        y = x_ref[:].astype(jnp.float32)
        si = 0
        for i, (R, k, p, q, off, L, hbp) in enumerate(meta):
            if p:
                z = jnp.concatenate([scr[si][:], y], axis=0)
                if off:
                    scr[si][:off] = jnp.zeros((off, CB), jnp.float32)
                scr[si][off:] = z[z.shape[0] - p :]
                cout[si][:] = z[z.shape[0] - p :]
                si += 1
            else:
                z = y
            zf = z.reshape(z.shape[0] // R, R, CB)
            acc = jnp.zeros((k, CB), jnp.float32)
            tv = taps[i][:]
            for b in range(hbp.shape[0]):
                # the partial first/last frames are SLICED to the true
                # tap support [off, off + L): multiplying the padded
                # positions by their zero taps instead would turn a
                # NaN-gap row into 0 * NaN = NaN and smear NaN outside
                # the receptive field (the per-stage polyphase path
                # pays that smear only FORWARD; slicing keeps this
                # kernel's NaN set a subset of the reference's)
                lo = max(0, off - b * R)
                hi = min(R, off + L - b * R)
                if hi <= lo:
                    continue
                acc = acc + jnp.sum(
                    zf[b : b + k, lo:hi] * tv[b, lo:hi][None, :, None],
                    axis=1,
                )
            y = acc
        y_ref[:] = y

    return kernel


def fused_cascade_pallas(
    x, bufs, stages, sizes, chunk_out: int, interpret: bool = False,
    cb=None,
):
    """One fused stateful cascade step: x (T, C) f32, ``bufs`` the
    per-stage carry tuple ((p_i, C) each, the same layout every other
    engine carries) -> (y (T/ratio, C), new_bufs).

    ``T`` must be a multiple of ``chunk_out * ratio`` (the caller
    picks ``chunk_out`` dividing the block's output count —
    :func:`tpudas.ops.fir.fused_chunk_outputs`).  ``stages`` are the
    plan's (R, taps) pairs with CONCRETE taps; ``x``/``bufs`` may be
    traced.  Channel counts that are not lane-block multiples get
    whole-block zero padding (carry columns included — zero columns
    stay zero through the linear stages, so the trim is exact)."""
    CB = channel_block() if cb is None else int(cb)
    T, C = x.shape
    ratio = 1
    for R, _h in stages:
        ratio *= int(R)
    chunk_in = int(chunk_out) * ratio
    if T % chunk_in:
        raise ValueError(
            f"fused kernel block ({T} rows) is not a multiple of the "
            f"chunk ({chunk_in} rows)"
        )
    nt = T // chunk_in
    nc = _round_up_div(C, CB)
    pad_c = nc * CB - C
    if pad_c:
        x = jnp.pad(x, ((0, 0), (0, pad_c)))
        bufs = tuple(jnp.pad(b, ((0, 0), (0, pad_c))) for b in bufs)
    meta = _fused_stage_meta(stages, sizes, chunk_in)
    state = [(i, q, p) for i, (_R, _k, p, q, _off, _L, _h) in
             enumerate(meta) if p]

    grid_spec = dict(
        grid=(nc, nt),
        in_specs=[
            *[
                pl.BlockSpec(
                    tuple(hbp.shape), lambda c, t: (0, 0),
                    memory_space=pltpu.VMEM,
                )
                for _R, _k, _p, _q, _off, _L, hbp in meta
            ],
            pl.BlockSpec(
                (chunk_in, CB), lambda c, t: (t, c),
                memory_space=pltpu.VMEM,
            ),
            *[
                pl.BlockSpec(
                    (p, CB), lambda c, t: (0, c),
                    memory_space=pltpu.VMEM,
                )
                for _i, _q, p in state
            ],
        ],
        out_specs=[
            pl.BlockSpec(
                (int(chunk_out), CB), lambda c, t: (t, c),
                memory_space=pltpu.VMEM,
            ),
            *[
                pl.BlockSpec(
                    (p, CB), lambda c, t: (0, c),
                    memory_space=pltpu.VMEM,
                )
                for _i, _q, p in state
            ],
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T // ratio, nc * CB), jnp.float32),
            *[
                jax.ShapeDtypeStruct((p, nc * CB), jnp.float32)
                for _i, _q, p in state
            ],
        ],
        scratch_shapes=[
            pltpu.VMEM((q, CB), jnp.float32) for _i, q, _p in state
        ],
    )
    outs = pl.pallas_call(
        _fused_kernel_body(meta, CB),
        interpret=interpret,
        **grid_spec,
    )(
        *[jnp.asarray(hbp) for _R, _k, _p, _q, _off, _L, hbp in meta],
        x.astype(jnp.float32),
        *[bufs[i] for i, _q, _p in state],
    )
    y = outs[0][:, :C] if pad_c else outs[0]
    new_tails = iter(outs[1:])
    new_bufs = []
    for i, b in enumerate(bufs):
        if int(b.shape[0]):
            nb = next(new_tails)
            new_bufs.append(nb[:, :C] if pad_c else nb)
        else:
            new_bufs.append(b[:, :C] if pad_c else b)
    return y, tuple(new_bufs)
