"""Pallas TPU kernel: strided (decimating) FIR along the time axis.

This is the hot inner loop of the cascade engine (tpudas.ops.fir): for
a (T, C) block and frame-blocked taps ``hb`` (B, R),

    y[k, c] = sum_{b, r} hb[b, r] * x[(k + b) * R + r, c]

i.e. a causal FIR of length <= B*R evaluated only at stride-R output
positions — the op the reference executes as full-rate ``sosfiltfilt``
+ decimating ``interpolate`` (lf_das.py:223-225) and XLA executes as
B shifted matmuls with B full HBM passes.

Design (v2, informed by on-chip measurement — see PERF.md §4):

- **MXU banded matmul, not VPU shifted adds.**  For an SB-frame output
  sub-block the FIR is one dot ``Y = A @ X`` with
  ``A[k, k*R + j] = h[j]`` the (SB, (SB+HALO)*R) banded tap matrix and
  ``X`` the flat 2-D view of the input rows.  A is ~96% zeros, but the
  MXU has ~50x the VPU's throughput: the VPU formulation measured
  compute-bound at 174 GB/s while this one is bound by the DMA stream.
  A rides along as a grid-constant input (index map (0,0)): the
  pipeline fetches it once and skips the re-DMA on later steps.
- **P parallel input streams.**  A single auto-pipelined input block
  measured ~185 GB/s regardless of block geometry (one DMA in flight
  can't cover HBM latency).  Each grid step therefore reads P separate
  main blocks — P views of the same array at consecutive block
  indices, each with its own double buffer and in-flight DMA.
- **f32 accuracy via a 3-pass bf16 split** (hi/lo split of both
  operands, dropping lo*lo): Mosaic lowers only DEFAULT (1-pass bf16,
  ~3e-3 abs error on unit-scale data — too coarse) and HIGHEST
  (6-pass); 3 passes give ~1e-5 at half HIGHEST's MXU cost.  Interpret
  mode (the CPU test path) uses exact f32 dots instead, so CPU
  equality tests see the mathematically exact kernel.

Layout: the halo of main block j is the head of main block j+1 — for
j < P-1 that block is already resident in the same grid step, so only
the LAST sub-block needs a dedicated halo input (the head of the next
step's first main block, expressed as a second BlockSpec over the same
array; possible because HALO_F divides SB, so the halo offset is an
integer block index).

VMEM at (P, SB, CB) = (4, 128, 128), R=8: 4 mains x 512 KB x 2
(double-buffered) + A 557 KB + out 256 KB x 2 + halo 32 KB x 2 — about
6 MB of the ~16 MB budget.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fir_decimate_pallas", "stage_input_rows"]

_SB = 128  # output frames per sub-block (one MXU dot)


def _env_geom(name: str, default: int, multiple_of: int = 1) -> int:
    """Env-tunable geometry knob: empty/unset -> default; anything
    else must be a positive int (and a lane multiple where required)
    — fail at import with the variable named, not mid-run."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val < 1 or val % multiple_of:
        raise ValueError(
            f"{name} must be a positive multiple of {multiple_of}, "
            f"got {val}"
        )
    return val


# geometry is env-tunable so on-chip sweeps need no code edits; the
# engine's chain layout reads the same constants, keeping the sizing
# math and the kernel grid in lockstep
_P = _env_geom("TPUDAS_PALLAS_P", 4)  # parallel DMA streams
_KB = _SB * _P  # output frames per grid step (the grid quantum)
_CB = _env_geom("TPUDAS_PALLAS_CB", 128, multiple_of=128)  # channel block


def _mosaic_knobs():
    """Experimental Mosaic/pipeline knobs for on-chip sweeps (read at
    call time so one process can A/B them without reimport):

    - TPUDAS_PALLAS_DIMSEM: dimension_semantics for the (k, c) grid —
      "parallel", "arbitrary", or a comma pair like
      "arbitrary,parallel" (order follows the ACTIVE grid order).
    - TPUDAS_PALLAS_GRID: "kc" (default; channel block varies fastest)
      or "ck" (output-frame block varies fastest, so consecutive grid
      steps walk sequential rows of the input).
    - TPUDAS_PALLAS_VMEM_MB: vmem_limit_bytes override, in MiB —
      larger double-buffering headroom for big-block geometries.

    Defaults leave everything unset: identical behavior/lowering to
    the kernel that passed chip_check (chip_r05/chip_check.log).
    """
    sems_env = os.environ.get("TPUDAS_PALLAS_DIMSEM", "").strip()
    grid_order = os.environ.get("TPUDAS_PALLAS_GRID", "kc").strip() or "kc"
    if grid_order not in ("kc", "ck"):
        raise ValueError(
            f"TPUDAS_PALLAS_GRID must be 'kc' or 'ck', got {grid_order!r}"
        )
    vmem_mb = _env_geom("TPUDAS_PALLAS_VMEM_MB", 0)  # 0 = unset
    cp_kwargs = {}
    if sems_env:
        sems = tuple(s.strip() for s in sems_env.split(","))
        if len(sems) == 1:
            sems = sems * 2
        if len(sems) != 2 or not all(
            s in ("parallel", "arbitrary") for s in sems
        ):
            raise ValueError(
                "TPUDAS_PALLAS_DIMSEM must be 'parallel', 'arbitrary' "
                f"or a comma pair of those, got {sems_env!r}"
            )
        cp_kwargs["dimension_semantics"] = sems
    if vmem_mb:
        cp_kwargs["vmem_limit_bytes"] = vmem_mb * 2**20
    call_kwargs = {}
    if cp_kwargs:
        # renamed TPUCompilerParams -> CompilerParams across jax
        # versions; accept either spelling
        params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        call_kwargs["compiler_params"] = params_cls(**cp_kwargs)
    return grid_order, call_kwargs


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _halo_frames(B: int, sb: int = _SB) -> int:
    """Halo frames: B rounded up to a sublane multiple that also
    divides the sub-block (so the halo offset is an integer block
    index). Single source for both the kernel and the sizing math."""
    halo_f = _round_up(B, 8)
    while halo_f <= sb and sb % halo_f != 0:
        halo_f += 8
    return halo_f


def stage_input_rows(B: int, R: int, n_out: int, kb: int = _KB) -> int:
    """Input rows this kernel consumes to emit ``n_out`` outputs with
    B tap-frames at stride R — the grid/halo-padded figure. Feeding
    exactly this many rows makes the kernel pad-free (the internal
    ``jnp.pad`` otherwise materializes a full copy of the input, which
    at engine scale is an extra HBM round-trip per stage)."""
    sb = min(int(kb), _SB)
    return (_round_up(int(n_out), kb) + _halo_frames(B, sb)) * R


def _split_bf16(v):
    hi = v.astype(jnp.bfloat16)
    lo = (v - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _dot_3x(a, x):
    """~f32-accurate matmul from 3 bf16 MXU passes (drops lo*lo)."""
    a_hi, a_lo = _split_bf16(a)
    x_hi, x_lo = _split_bf16(x)
    d = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    return d(a_hi, x_hi) + d(a_hi, x_lo) + d(a_lo, x_hi)


def _dot_f32(a, x):
    return jnp.dot(
        a,
        x,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _kernel_body(P, SB, CB, halo_rows, exact):
    dot = _dot_f32 if exact else _dot_3x

    def kernel(*refs):
        a_ref = refs[0]
        mains = refs[1 : 1 + P]
        halo_ref = refs[1 + P]
        out_ref = refs[2 + P]
        for j in range(P):
            head = (
                mains[j + 1][:halo_rows]
                if j < P - 1
                else halo_ref[:]
            )
            x = jnp.concatenate([mains[j][:], head], axis=0)
            # int16 ingest: bare cast in VMEM after the (half-width)
            # DMA — the quantization scale is the caller's (applied to
            # the decimated output; the FIR is linear).  Exact under
            # the 3x split too: a 16-bit integer is hi+lo bf16 exactly.
            x = x.astype(jnp.float32)
            out_ref[j * SB : (j + 1) * SB] = dot(a_ref[:], x)

    return kernel


@functools.lru_cache(maxsize=64)
def _band_matrix(taps: tuple, R: int, SB: int, rows: int) -> np.ndarray:
    h = np.asarray(taps, np.float32)
    A = np.zeros((SB, rows), np.float32)
    for k in range(SB):
        A[k, k * R : k * R + len(h)] = h
    return A


# ---------------------------------------------------------------------------
# v1 implementation (VPU shifted multiply-reduce): the kernel behind the
# proven 29.06 G ch-samp/s on-chip record (PERF.md §3).  Kept selectable
# via TPUDAS_PALLAS_IMPL=v1 — and as the bench's automatic middle
# fallback — until the v2 MXU kernel has been validated by Mosaic on
# real hardware (it has only interpret-mode coverage; PERF.md §5).


def _kernel_body_v1(B, KB, CB):
    def kernel(hb_ref, xm_ref, xh_ref, out_ref):
        full = jnp.concatenate(
            [xm_ref[:], xh_ref[:]], axis=0
        ).astype(jnp.float32)
        acc = jnp.zeros((KB, CB), jnp.float32)
        for b in range(B):
            acc = acc + jnp.sum(
                full[b : b + KB] * hb_ref[b][None, :, None], axis=1
            )
        out_ref[:] = acc

    return kernel


def _fir_decimate_pallas_v1(x, hb, R: int, n_out: int,
                            interpret: bool = False):
    """The round-4 session-1 kernel: 128-frame blocks, taps as a VMEM
    operand, B shifted VPU multiply-reduces.  Accepts int16 input
    (bare cast, like v2).  Tolerates inputs sized for the v2 grid
    quantum (it slices its own, smaller need)."""
    KB = CB = 128
    hb = np.asarray(hb)
    B = int(hb.shape[0])
    T, C = x.shape
    halo_f = _halo_frames(B, KB)
    if halo_f > KB:
        raise ValueError(
            f"tap frames ({B}) exceed the kernel block ({KB} frames); "
            "use the XLA polyphase path for very long stages"
        )
    nk = -(-int(n_out) // KB)
    nc = -(-int(C) // CB)
    Kpad = nk * KB
    need_rows = (Kpad + halo_f) * R
    pad_t = need_rows - T
    pad_c = nc * CB - C
    if pad_t > 0 or pad_c > 0:
        x = jnp.pad(x, ((0, max(pad_t, 0)), (0, pad_c)))
    xr = x[:need_rows].reshape(Kpad + halo_f, R, nc * CB)
    hb_pad = np.zeros((halo_f, R), np.float32)
    hb_pad[:B] = hb.astype(np.float32)
    step = KB // halo_f
    out = pl.pallas_call(
        _kernel_body_v1(B, KB, CB),
        grid=(nk, nc),
        in_specs=[
            pl.BlockSpec(
                (halo_f, R), lambda k, c: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (KB, R, CB),
                lambda k, c: (k, 0, c),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (halo_f, R, CB),
                lambda k, c, _s=step: (k * _s + _s, 0, c),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (KB, CB), lambda k, c: (k, c), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Kpad, nc * CB), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(hb_pad), xr, xr)
    return out[:n_out, :C]


def fir_decimate_pallas(
    x, hb, R: int, n_out: int, interpret: bool = False, kb=_KB, cb=_CB
):
    """Strided FIR: x (T, C) f32 or int16, hb (B, R) f32 -> (n_out, C)
    f32.

    ``hb`` must be CONCRETE (host numpy or a settled device array, not
    a tracer): the banded tap matrix is built on the host.  ``x`` may
    be traced — callers jit the enclosing cascade.  ``n_out`` is
    static; the input is zero-padded on the right as needed (outputs
    whose receptive field crosses the pad carry edge artifacts,
    trimmed by the overlap-save caller), and channel counts that are
    not multiples of the lane tile get whole-block zero padding.
    ``kb`` is the grid quantum in output frames (P parallel sub-blocks
    of min(kb, 128) frames each); ``cb`` the channel block.

    int16 ``x`` (the tdas quantized-ingest payload) is cast to f32 in
    VMEM after the half-width DMA and filtered RAW — the caller owns
    the quantization scale and, the FIR being linear, applies it to
    this stage's (decimated, so R-times smaller) output.  Keeping the
    scale out of the kernel keeps it a traced value: one compiled
    executable serves every scale.

    ``TPUDAS_PALLAS_IMPL=v1`` selects the previous VPU formulation
    (the proven-on-hardware kernel; see the v1 section below).
    """
    if os.environ.get("TPUDAS_PALLAS_IMPL", "v2") == "v1":
        return _fir_decimate_pallas_v1(x, hb, R, n_out, interpret)
    B = int(hb.shape[0])
    T, C = x.shape
    KB, CB = int(kb), int(cb)
    SB = min(KB, _SB)
    P = KB // SB
    if KB % SB:
        raise ValueError(f"kb ({KB}) must be a multiple of {SB}")
    halo_f = _halo_frames(B, SB)
    if halo_f > SB:
        raise ValueError(
            f"tap frames ({B}) exceed the kernel sub-block ({SB} "
            "frames); use the XLA polyphase path for very long stages"
        )

    nk = -(-int(n_out) // KB)
    nc = -(-int(C) // CB)
    Kpad = nk * KB
    need_rows = stage_input_rows(B, R, n_out, KB)
    pad_t = need_rows - T
    pad_c = nc * CB - C
    if pad_t > 0 or pad_c > 0:
        x = jnp.pad(x, ((0, max(pad_t, 0)), (0, pad_c)))
    x2 = x[:need_rows]

    # frame-blocked taps (B, R) flatten back to the padded tap vector
    taps = tuple(np.asarray(jax.device_get(hb), np.float32).reshape(-1))
    band_rows = (SB + halo_f) * R
    A = jnp.asarray(_band_matrix(taps, R, SB, band_rows))

    halo_rows = halo_f * R
    step = SB * P // halo_f  # halo offset in halo-block units

    grid_order, call_kwargs = _mosaic_knobs()
    if grid_order == "ck":
        # grid (nc, nk): index-map args arrive as (c, k) — remap to
        # the (k, c) the block coordinates are written in
        grid = (nc, nk)

        def _km(f):
            return lambda c, k, _f=f: _f(k, c)

    else:
        grid = (nk, nc)

        def _km(f):
            return f

    main_specs = [
        pl.BlockSpec(
            (SB * R, CB),
            _km(lambda k, c, j=j: (k * P + j, c)),
            memory_space=pltpu.VMEM,
        )
        for j in range(P)
    ]
    halo_spec = pl.BlockSpec(
        (halo_rows, CB),
        _km(lambda k, c, _s=step: (k * _s + _s, c)),
        memory_space=pltpu.VMEM,
    )
    out = pl.pallas_call(
        _kernel_body(P, SB, CB, halo_rows, exact=interpret),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (SB, band_rows),
                _km(lambda k, c: (0, 0)),
                memory_space=pltpu.VMEM,
            ),
            *main_specs,
            halo_spec,
        ],
        out_specs=pl.BlockSpec(
            (KB, CB), _km(lambda k, c: (k, c)), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Kpad, nc * CB), jnp.float32),
        interpret=interpret,
        **call_kwargs,
    )(A, *([x2] * P), x2)
    return out[:n_out, :C]
