"""Pallas TPU kernel: strided (decimating) FIR along the time axis.

This is the hot inner loop of the cascade engine (tpudas.ops.fir): for
a (T, C) block and frame-blocked taps ``hb`` (B, R),

    y[k, c] = sum_{b, r} hb[b, r] * x[(k + b) * R + r, c]

i.e. a causal FIR of length <= B*R evaluated only at stride-R output
positions — the op the reference executes as full-rate ``sosfiltfilt``
+ decimating ``interpolate`` (lf_das.py:223-225) and XLA executes as
B shifted matmuls with B full HBM passes. The kernel reads each input
element exactly once into VMEM and does all B shifted reductions
on-chip.

Layout: the input is viewed as frames ``(K + halo, R, C)`` (a free
reshape — time-major data is already contiguous). The grid is
``(K/KB, C/CB)``; each program gets its main frame block ``(KB, R, CB)``
plus a ``(HALO_F, R, CB)`` halo block that is simply the head of the
next main block, expressed as a second BlockSpec over the same array
(possible because HALO_F divides KB, so the halo offset is an integer
block index). Mosaic double-buffers both streams automatically.

Tiling: KB=128 frames, CB=128 lanes (f32 min tile is (8, 128); R is
the middle dim of the 3-D block). The tap table rides along as a
(HALO_F, R) VMEM operand. VMEM per program at R=8:
128*8*128*4B = 512 KB main + 32 KB halo + 64 KB out — comfortably
inside the ~16 MB budget even with double buffering.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fir_decimate_pallas"]

_KB = 128  # output frames per program (sublane-aligned multiple of 8)
_CB = 128  # channels per program (lane width)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _halo_frames(B: int, kb: int) -> int:
    """Halo block frames: B rounded up to a sublane multiple that also
    divides the main block (so the halo offset is an integer block
    index). Single source for both the kernel and the sizing math."""
    halo_f = _round_up(B, 8)
    while halo_f <= kb and kb % halo_f != 0:
        halo_f += 8
    return halo_f


def stage_input_rows(B: int, R: int, n_out: int, kb: int = _KB) -> int:
    """Input rows this kernel consumes to emit ``n_out`` outputs with
    B tap-frames at stride R — the grid/halo-padded figure. Feeding
    exactly this many rows makes the kernel pad-free (the internal
    ``jnp.pad`` otherwise materializes a full copy of the input, which
    at engine scale is an extra HBM round-trip per stage)."""
    return (_round_up(int(n_out), kb) + _halo_frames(B, kb)) * R


def _kernel_body(B, KB, CB):
    def kernel(hb_ref, xm_ref, xh_ref, out_ref):
        full = jnp.concatenate([xm_ref[:], xh_ref[:]], axis=0)
        acc = jnp.zeros((KB, CB), jnp.float32)
        for b in range(B):
            acc = acc + jnp.sum(
                full[b : b + KB] * hb_ref[b][None, :, None], axis=1
            )
        out_ref[:] = acc

    return kernel


@functools.partial(
    jax.jit, static_argnames=("R", "n_out", "interpret", "kb", "cb")
)
def fir_decimate_pallas(
    x, hb, R: int, n_out: int, interpret: bool = False, kb=_KB, cb=_CB
):
    """Strided FIR: x (T, C) f32, hb (B, R) f32 -> (n_out, C) f32.

    ``n_out`` is static; the input is zero-padded on the right as
    needed (outputs whose receptive field crosses the pad carry edge
    artifacts, trimmed by the overlap-save caller). Falls back to
    whole-block zero padding for channel counts that are not multiples
    of the 128-lane tile.
    """
    B = int(hb.shape[0])
    T, C = x.shape
    KB, CB = int(kb), int(cb)
    halo_f = _halo_frames(B, KB)
    if halo_f > KB:
        raise ValueError(
            f"tap frames ({B}) exceed the kernel block ({KB} frames); "
            "use the XLA polyphase path for very long stages"
        )

    nk = -(-int(n_out) // KB)
    nc = -(-int(C) // CB)
    Kpad = nk * KB
    need_rows = stage_input_rows(B, R, n_out, KB)
    pad_t = need_rows - T
    pad_c = nc * CB - C
    if pad_t > 0 or pad_c > 0:
        x = jnp.pad(x, ((0, max(pad_t, 0)), (0, pad_c)))
    xr = x[:need_rows].reshape(Kpad + halo_f, R, nc * CB)

    hb_pad = jnp.zeros((halo_f, R), jnp.float32).at[:B].set(
        hb.astype(jnp.float32)
    )
    step = KB // halo_f

    out = pl.pallas_call(
        _kernel_body(B, KB, CB),
        grid=(nk, nc),
        in_specs=[
            pl.BlockSpec(
                (halo_f, R), lambda k, c: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (KB, R, CB),
                lambda k, c: (k, 0, c),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (halo_f, R, CB),
                lambda k, c, _s=step: (k * _s + _s, 0, c),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (KB, CB), lambda k, c: (k, c), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Kpad, nc * CB), jnp.float32),
        interpret=interpret,
    )(hb_pad, xr, xr)
    return out[:n_out, :C]
