"""Rolling-window reductions with pandas-compatible semantics.

The reference computes mean-decimation via
``patch.rolling(time=w, step=s, engine="numpy").mean()``
(rolling_mean_dascore.ipynb:148). Semantics (DASCore mimics pandas
``rolling(window, step=step)``):

- output positions are input indices ``p = 0, s, 2s, ...`` (so the
  output time coord is ``time[::s]``),
- the window at position ``p`` is the trailing ``[p-w+1, p]``,
- positions with ``p < w-1`` (incomplete window) are NaN — the warm-up
  prefix downstream strips with ``dropna("time")``.

TPU engine: ``lax.reduce_window`` (pairwise tree reduction — accurate in
f32, fuses, maps to the VPU) on the alignment-shifted array, NaN prefix
concatenated. Host engine: float64 cumsum / stride tricks.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from tpudas.core import units as _units

__all__ = ["PatchRoller", "rolling_reduce", "rolling_mean_patches_batched"]


def _window_step_samples(window_sec, step_sec, d_sec):
    w = int(round(window_sec / d_sec))
    s = int(round(step_sec / d_sec)) if step_sec is not None else 1
    if w < 1:
        raise ValueError(f"window shorter than one sample ({window_sec} s)")
    if s < 1:
        raise ValueError(f"step shorter than one sample ({step_sec} s)")
    return w, s


@functools.partial(jax.jit, static_argnames=("w", "s", "op"))
def _reduce_window_kernel(data, w, s, op):
    """data: (T, C). Valid trailing windows at stride s, pandas-aligned.

    Returns the full output (including NaN warm-up rows).
    """
    n = data.shape[0]
    n_out = (n - 1) // s + 1  # positions 0, s, 2s, ... < n
    i0 = -(-(w - 1) // s)  # ceil((w-1)/s): first complete-window output
    if i0 >= n_out:  # static: no window ever completes
        return jnp.full((n_out,) + data.shape[1:], jnp.nan, data.dtype)
    j0 = i0 * s - w + 1  # input start so valid windows land on positions
    x = data[j0:]
    if op == "mean" or op == "sum":
        init, fn = 0.0, jax.lax.add
    elif op == "max":
        init, fn = -jnp.inf, jax.lax.max
    elif op == "min":
        init, fn = jnp.inf, jax.lax.min
    else:
        raise ValueError(op)
    red = jax.lax.reduce_window(
        x,
        jnp.asarray(init, data.dtype),
        fn,
        window_dimensions=(w,) + (1,) * (data.ndim - 1),
        window_strides=(s,) + (1,) * (data.ndim - 1),
        padding="valid",
    )
    if op == "mean":
        red = red / w
    nan_rows = jnp.full((i0,) + data.shape[1:], jnp.nan, data.dtype)
    return jnp.concatenate([nan_rows, red], axis=0)


def _host_rolling(data, w, s, op):
    """float64 host reference (pandas semantics, no pandas dependency)."""
    n = data.shape[0]
    positions = np.arange(0, n, s)
    out = np.full((len(positions),) + data.shape[1:], np.nan, dtype=np.float64)
    x = data.astype(np.float64)
    if op in ("mean", "sum"):
        c = np.cumsum(x, axis=0)
        zero = np.zeros((1,) + x.shape[1:])
        c = np.concatenate([zero, c], axis=0)  # c[k] = sum of first k
        valid = positions >= w - 1
        pv = positions[valid]
        ssum = c[pv + 1] - c[pv + 1 - w]
        out[valid] = ssum / w if op == "mean" else ssum
    else:
        # vectorized trailing-window extrema via a strided view:
        # windows[j] = x[j : j + w], so position p maps to window
        # p - (w - 1).  The view is copy-free but the position gather
        # is not, so reduce in bounded batches (~16M elements of
        # float64 at a time) — dense positions with a large window
        # must not materialize O(positions * w * channels) at once.
        # NaN propagates exactly as the old per-position loop did.
        fn = np.max if op == "max" else np.min
        valid = positions >= w - 1
        pv = positions[valid] - (w - 1)
        if pv.size:
            windows = np.lib.stride_tricks.sliding_window_view(
                x, w, axis=0
            )
            row_elems = w * int(np.prod(x.shape[1:], dtype=np.int64))
            batch = max(int(16_000_000 // max(row_elems, 1)), 1)
            reduced = np.empty((pv.size,) + x.shape[1:], np.float64)
            for b0 in range(0, pv.size, batch):
                sel = pv[b0 : b0 + batch]
                reduced[b0 : b0 + len(sel)] = fn(windows[sel], axis=-1)
            out[valid] = reduced
    return out


def rolling_reduce(data, w, s, op, axis=0, engine=None):
    """Rolling reduction along ``axis`` with pandas alignment."""
    if engine in ("numpy", "host"):
        host = np.asarray(data)
        moved = axis != 0
        if moved:
            host = np.moveaxis(host, axis, 0)
        out = _host_rolling(host, w, s, op).astype(np.float64)
        if moved:
            out = np.moveaxis(out, 0, axis)
        return out
    arr = jnp.asarray(data)
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        arr = arr.astype(jnp.float32)
    moved = axis != 0
    if moved:
        arr = jnp.moveaxis(arr, axis, 0)
    out = _reduce_window_kernel(arr, int(w), int(s), op)
    if moved:
        out = jnp.moveaxis(out, 0, axis)
    return out


class PatchRoller:
    """Factory returned by ``patch.rolling(time=w, step=s, engine=...)``."""

    def __init__(self, patch, step=None, engine=None, **kwargs):
        if len(kwargs) != 1:
            raise ValueError("rolling requires exactly one dim, e.g. time=1*s")
        (dim, window), = kwargs.items()
        self.patch = patch
        self.dim = dim
        self.engine = engine
        d = patch.get_sample_step(dim)
        if d is None or d <= 0:
            raise ValueError(f"cannot infer sample step for dim {dim!r}")
        self.window, self.step = _window_step_samples(
            _units.get_seconds(window), _units.get_seconds(step), d
        )

    def _stepped_coords_attrs(self, p):
        """Subsampled coords + attrs with the *_step refreshed to the
        post-decimation step (stale steps would corrupt any downstream
        Nyquist / window / contiguity computation)."""
        from tpudas.core.attrs import derive_coord_attrs

        coords = dict(p.coords)
        coords[self.dim] = p.coords[self.dim][:: self.step]
        attrs = p.attrs.to_dict()
        attrs.update(derive_coord_attrs(coords, p.dims))
        return coords, attrs

    def _apply(self, op):
        p = self.patch
        ax = p.axis_of(self.dim)
        out = rolling_reduce(
            p.data, self.window, self.step, op, axis=ax, engine=self.engine
        )
        coords, attrs = self._stepped_coords_attrs(p)
        return p.new(data=out, coords=coords, attrs=attrs)

    def mean(self):
        return self._apply("mean")

    def sum(self):
        return self._apply("sum")

    def min(self):
        return self._apply("min")

    def max(self):
        return self._apply("max")

    def std(self):
        """Population std on the same windows.

        Computed on offset-shifted data ``y = x - mean(x)`` before the
        ``E[y^2] - E[y]^2`` identity: with a large DC offset (common in
        raw strain-rate counts) the unshifted identity cancels
        catastrophically in f32 — the two terms agree to ~offset^2 and
        the variance drowns in rounding. Shifting makes the residual
        means window-scale, so the subtraction is well conditioned.
        """
        p = self.patch
        ax = p.axis_of(self.dim)
        host = self.engine in ("numpy", "host")
        xp = np if host else jnp
        data = (
            np.asarray(p.data, np.float64) if host else jnp.asarray(p.data)
        )
        if not host and not jnp.issubdtype(data.dtype, jnp.floating):
            data = data.astype(jnp.float32)
        # nanmean + nan_to_num: a single NaN gap sample must only NaN
        # the windows that overlap it (as mean/sum do), not poison the
        # whole channel through the shift
        shift = xp.nan_to_num(
            xp.nanmean(data, axis=ax, keepdims=True), nan=0.0
        )
        y = data - shift
        m = rolling_reduce(
            y, self.window, self.step, "mean", axis=ax, engine=self.engine
        )
        m2 = rolling_reduce(
            y * y, self.window, self.step, "mean", axis=ax,
            engine=self.engine,
        )
        var = xp.maximum(m2 - m**2, 0)
        out = xp.sqrt(var)
        coords, attrs = self._stepped_coords_attrs(p)
        return p.new(data=out, coords=coords, attrs=attrs)


def rolling_mean_patches_batched(mesh, patches, window, step):
    """Data-parallel rolling mean of shape-uniform patches over the
    mesh's ``ch`` axis (SURVEY §2.4 DP row: independent patches are the
    trivial parallel axis). The batch is zero-padded to the shard
    multiple and trimmed after; per-patch output is byte-identical to
    the single-patch jax engine (same reduce_window kernel, vmapped).

    Lives beside :class:`PatchRoller` so the window/step derivation and
    coords/attrs reconstruction have exactly one owner. Returns the
    list of result patches, or ``None`` when the batch is not uniform
    enough to stack (callers fall back to per-patch).
    """
    from tpudas.parallel.batch import batched_rolling_mean

    first = patches[0]
    ax = first.axis_of("time")
    if any(
        p.shape != first.shape
        or p.dims != first.dims
        or p.get_sample_step("time") != first.get_sample_step("time")
        for p in patches
    ):
        return None
    # one PatchRoller per patch: validates and owns (w, s) + the
    # stepped coords/attrs semantics (uniform by the check above)
    rollers = [p.rolling(time=window, step=step) for p in patches]
    w, s = rollers[0].window, rollers[0].step
    stack = np.stack(
        [
            np.moveaxis(p.host_data(), ax, 0) if ax != 0 else p.host_data()
            for p in patches
        ]
    )
    nb = mesh.shape["ch"]
    pad_b = -len(patches) % nb
    if pad_b:
        stack = np.concatenate(
            [stack, np.zeros((pad_b,) + stack.shape[1:], stack.dtype)]
        )
    out = np.asarray(batched_rolling_mean(mesh, stack, w=w, s=s))
    results = []
    for i, (p, roller) in enumerate(zip(patches, rollers)):
        data = out[i]
        if ax != 0:
            data = np.moveaxis(data, 0, ax)
        coords, attrs = roller._stepped_coords_attrs(p)
        results.append(p.new(data=data, coords=coords, attrs=attrs))
    return results
