"""Multistage polyphase FIR decimation — the fast path of the engine.

The reference's hot loop filters the FULL-rate stream with a zero-phase
IIR and then throws away ~99.9% of the samples at the interpolation
step (reference lf_das.py:223-225: ``pass_filter`` at corner
``0.45/dt`` followed by ``interpolate`` onto the decimated grid). The
FFT engine (tpudas.ops.filter) reproduces that shape faithfully but
pays O(T log T) and several full-rate HBM passes per window.

This module exploits the decimating structure instead: a cascade of
small linear-phase FIR stages, each decimating by an integer factor,
designed so the *composite* magnitude response matches the reference's
zero-phase Butterworth-squared response ``1/(1+(f/fc)^(2*order))`` on
the retained band. Compute per input sample drops from O(log T) FFT
passes to ~4-6 multiply-adds, all in one streaming pass — the shape
TPUs (and the Pallas kernel in tpudas.ops.pallas_fir) like.

Design scheme
-------------
- ``factor_ratio`` splits the decimation ratio into integer stages
  (large factors first, so the full-rate stage is the cheapest).
- every stage except the last is a plain anti-alias guard: a
  Kaiser-windowed low-pass whose stopband starts where energy would
  fold back into the final retained band. Its passband covers the
  final band with ~1e-4 ripple.
- the last stage is *response-matched*: a zero-phase frequency-sampled
  FIR of the desired composite response divided by the measured
  response of the guard stages, so the cascade's end-to-end magnitude
  equals the Butterworth-squared target within truncation ripple.
- all stages have odd length, so the composite group delay is an
  integer number of full-rate samples (``CascadePlan.delay``); the
  caller re-indexes outputs by that delay, which makes the cascade
  zero-phase exactly like the reference's forward-backward filter.

Correctness is tolerance-based against the FFT engine (the same way
the reference treats its own edges: the self-calibration probe at
lf_das.py:47-87 thresholds the impulse response at ``max*tol``);
``impulse_response``/``edge_support_samples`` provide that probe for
this engine analytically.
"""

from __future__ import annotations

import functools
import time as _time
from dataclasses import dataclass

import numpy as np

__all__ = [
    "CascadePlan",
    "factor_ratio",
    "design_cascade",
    "cascade_decimate",
    "cascade_decimate_stream",
    "cascade_stream_init",
    "stream_carry_sizes",
    "stream_warmup_outputs",
    "stream_stage_engines",
    "impulse_response",
    "edge_support_samples",
    "butter2_mag",
    "resolve_cascade_engine",
    "resolve_stream_engine",
    "stage_engines",
    "knob_fingerprint",
    "fused_chunk_outputs",
    "fused_intermediate_bytes",
    "STREAM_ENGINES",
    "BATCH_ENGINES",
    "STACKED_ENGINES",
    "cascade_decimate_stream_stacked",
]

# engine literals the STREAM dispatch (cascade_decimate_stream)
# accepts: the per-stage chain with its own pallas/xla routing, plus
# the fused single-kernel formulations (ISSUE 10).  "fused" resolves
# by backend + measured size threshold (resolve_stream_engine); the
# -xla/-pallas spellings force a variant.  tools/check_engines.py
# lints that every literal here appears in the test matrix.
STREAM_ENGINES = ("auto", "pallas", "xla", "fused", "fused-xla",
                  "fused-pallas")
# engine literals the BATCH entry points (cascade_decimate & the
# window/batched paths) accept — the fused formulation is
# streaming-only (it exists to kill per-stage intermediates ACROSS
# carried blocks; the batch path's windows are one-shot).
BATCH_ENGINES = ("auto", "pallas", "xla")
# engine literals the STACKED multi-stream entry point
# (cascade_decimate_stream_stacked) accepts: RESOLVED non-Pallas
# stream variants only.  The fleet's batch executor routes a block
# here only after the per-stream solo resolution already chose one of
# these, so stacking can never flip a stream across the
# fused_min_elems threshold (the stacked width is larger than any
# member's solo width) and never silently swaps a tolerance-based
# Pallas variant for the exact XLA one.  tools/check_engines.py lints
# that every literal here appears in the test matrix.
STACKED_ENGINES = ("xla", "fused-xla")

# every env knob that changes kernel geometry or engine selection.
# knob_fingerprint() reads them at CALL time and every jit/layout
# cache key below includes the fingerprint, so a retune
# (tools/retune_stage_ok.py) applies mid-process — no restart, no
# manual cache clear (the stale-knob footgun this replaces).
_KNOB_ENVS = (
    "TPUDAS_PALLAS_P",
    "TPUDAS_PALLAS_CB",
    "TPUDAS_PALLAS_IMPL",
    "TPUDAS_PALLAS_MIN_ELEMS",
    "TPUDAS_STREAM_PALLAS",
    "TPUDAS_PALLAS_DIMSEM",
    "TPUDAS_PALLAS_GRID",
    "TPUDAS_PALLAS_VMEM_MB",
    "TPUDAS_FUSED_CHUNK",
    "TPUDAS_FUSED_MIN_ELEMS",
)


def knob_fingerprint() -> tuple:
    """The current value of every geometry/selector env knob, as one
    hashable tuple.  Threaded into every compiled-fn and layout cache
    key so a mid-process knob change can never hit a stale entry."""
    import os

    return tuple(os.environ.get(n, "").strip() for n in _KNOB_ENVS)


def _plan_tag(plan) -> str:
    """Compact stable tag of a CascadePlan for devprof kernel keys —
    distinct plans must read as distinct shapes, but the key has to
    stay printable (the /devprof kernel log shows it)."""
    return (
        f"r{plan.ratio}s{len(plan.stages)}"
        f"h{hash(plan) & 0xFFFFFF:06x}"
    )


def butter2_mag(f, corner, order):
    """The reference's zero-phase magnitude: ``|H_butter|^2`` of an
    ``order``-pole Butterworth low-pass (sosfiltfilt applies the filter
    twice, squaring the magnitude — tpudas.ops.filter matches this)."""
    f = np.asarray(f, np.float64)
    return 1.0 / (1.0 + (f / float(corner)) ** (2 * int(order)))


def factor_ratio(ratio: int) -> list[int]:
    """Split an integer decimation ratio into stage factors in [2, 8],
    largest first. Raises if a prime factor > 8 remains."""
    ratio = int(ratio)
    if ratio < 1:
        raise ValueError(f"decimation ratio must be >= 1, got {ratio}")
    factors = []
    rem = ratio
    while rem > 1:
        for f in (8, 7, 6, 5, 4, 3, 2):
            if rem % f == 0:
                factors.append(f)
                rem //= f
                break
        else:
            raise ValueError(
                f"ratio {ratio} has a prime factor > 8; "
                "use the FFT engine for this ratio"
            )
    factors.sort(reverse=True)
    return factors


@dataclass(frozen=True, eq=False)
class CascadePlan:
    """A compiled multistage decimation filter.

    stages: tuple of (R, taps) — taps are float32, odd length.
    ratio:  product of all R.
    delay:  composite group delay in FULL-RATE samples (integer,
            because every stage is odd-length linear-phase);
            causal cascade output ``k`` is the zero-phase filtered
            input at full-rate index ``k*ratio + delay``.
    fs_in / corner / order: the design point.

    Hash/eq are by tap content so plans can key jit caches.
    """

    stages: tuple
    ratio: int
    delay: int
    fs_in: float
    corner: float
    order: int

    @property
    def receptive_field(self) -> int:
        """Total taps footprint in full-rate samples (= 2*delay + 1)."""
        return 2 * self.delay + 1

    def _fingerprint(self):
        return (
            self.ratio,
            self.delay,
            tuple(
                (int(R), np.asarray(h).tobytes()) for R, h in self.stages
            ),
        )

    def __hash__(self):
        return hash(self._fingerprint())

    def __eq__(self, other):
        return (
            isinstance(other, CascadePlan)
            and self._fingerprint() == other._fingerprint()
        )


def _guard_stage_taps(fs_in: float, R: int, f_keep: float) -> np.ndarray:
    """Anti-alias guard: keep [0, f_keep] intact, attenuate everything
    that decimation by R would fold back onto [0, f_keep]."""
    from scipy.signal import firwin, kaiserord

    fs_out = fs_in / R
    stop = fs_out - f_keep  # first fold-back edge
    pass_edge = f_keep
    width = max(stop - pass_edge, 0.05 * fs_in / R)
    numtaps, beta = kaiserord(80.0, width / (0.5 * fs_in))
    numtaps = max(numtaps, 9)
    if numtaps % 2 == 0:
        numtaps += 1
    cutoff = 0.5 * (pass_edge + stop)
    return firwin(
        numtaps, cutoff, window=("kaiser", beta), fs=fs_in
    ).astype(np.float32)


def _stage_response(taps: np.ndarray, fs: float, freqs: np.ndarray):
    """Real-valued magnitude response of a symmetric (linear-phase) FIR
    at ``freqs`` Hz (phase removed analytically)."""
    n = np.arange(len(taps), dtype=np.float64) - (len(taps) - 1) / 2.0
    ang = 2.0 * np.pi * np.asarray(freqs, np.float64)[:, None] * n[None, :] / fs
    return (np.cos(ang) @ np.asarray(taps, np.float64)).astype(np.float64)


def _matched_last_stage(
    fs_l: float,
    corner: float,
    order: int,
    guard_resp,
    taps: int | None,
) -> np.ndarray:
    """Frequency-sampled zero-phase FIR matching
    ``butter2_mag / guard_resp`` on [0, fs_l/2]."""
    nfft = 16384
    freqs = np.arange(nfft // 2 + 1, dtype=np.float64) * fs_l / nfft
    desired = butter2_mag(freqs, corner, order)
    g = np.clip(guard_resp(freqs), 1e-3, None)
    d = np.where(desired > 1e-8, desired / g, 0.0)
    h_full = np.fft.irfft(d, n=nfft)  # symmetric around index 0
    h_c = np.concatenate([h_full[nfft // 2 :], h_full[: nfft // 2]])
    center = nfft // 2
    if taps is None:
        mag = np.abs(h_c)
        thresh = mag.max() * 1e-6
        above = np.nonzero(mag > thresh)[0]
        half = int(
            max(center - above[0], above[-1] - center, 4)
        )
        taps = min(2 * half + 1, 4095)
    if taps % 2 == 0:
        taps += 1
    half = taps // 2
    h = h_c[center - half : center + half + 1].copy()
    # no taper: the target response is smooth, so the frequency-sampled
    # impulse response decays below 1e-6 before truncation and plain
    # truncation keeps the band error ~1e-6 (a Kaiser taper would bias
    # the passband by ~1e-2). Renormalize DC to the exact target gain.
    dc_target = d[0]
    s = h.sum()
    if s != 0:
        h *= dc_target / s
    return h.astype(np.float32)


@functools.lru_cache(maxsize=64)
def design_cascade(
    fs_in: float,
    ratio: int,
    corner: float,
    order: int = 4,
    last_taps: int | None = None,
) -> CascadePlan:
    """Design the multistage decimator for ``fs_in -> fs_in/ratio`` with
    composite response ``butter2_mag(f, corner, order)``.

    The retained band is [0, 0.5*fs_in/ratio] (the output Nyquist);
    guard stages protect it from aliasing at >= 80 dB, and the last
    stage shapes the composite response to the Butterworth-squared
    target of the reference engine (lf_das.py:223).
    """
    factors = factor_ratio(ratio)
    f_out = fs_in / ratio
    f_keep = 0.5 * f_out
    stages = []
    fs = fs_in
    guard_list = []
    if len(factors) > 1:
        for R in factors[:-1]:
            h = _guard_stage_taps(fs, R, f_keep)
            stages.append((R, h))
            guard_list.append((h, fs))
            fs /= R
    R_last = factors[-1] if factors else 1

    def guard_resp(freqs):
        resp = np.ones_like(np.asarray(freqs, np.float64))
        for taps, fs_i in guard_list:
            resp = resp * _stage_response(taps, fs_i, freqs)
        return resp

    h_last = _matched_last_stage(fs, corner, order, guard_resp, last_taps)
    stages.append((R_last, h_last))

    delay = 0
    prod = 1
    for R, h in stages:
        delay += (len(h) // 2) * prod
        prod *= R
    assert prod == ratio
    return CascadePlan(
        stages=tuple((int(R), h) for R, h in stages),
        ratio=int(ratio),
        delay=int(delay),
        fs_in=float(fs_in),
        corner=float(corner),
        order=int(order),
    )


# ---------------------------------------------------------------------------
# application


def _polyphase_stage_xla(x, hb, R, n_out):
    """One causal decimating stage on (T, C) data:
    ``y[k, c] = sum_j h[j] x[k*R + j, c]`` for k in [0, n_out).

    hb is the (B, R) frame-blocked tap matrix (zero-padded taps).

    Phase-contracted formulation: one contraction over the tap phase
    ``r`` for ALL frames at once (``u[b, m] = <x frame m, hb[b]>``),
    then a B-term shifted sum over the small decimated frames.  The
    naive form (B shifted einsums over the full-rate input) re-reads
    the input B times; this reads it once plus ~B/R of it for ``u`` —
    the streaming stage is memory-bound at production widths, and the
    rewrite measures ~3x faster on stage 0 of the 1 kHz flagship plan
    at 10k channels on CPU (PERF.md
    "Sharded streaming").  The b-loop accumulates in the same order as
    the naive form, and each b-term is the same dot over ``r``, so
    per-element float arithmetic is unchanged in structure (the stage
    remains deterministic and layout-independent: channel columns are
    independent, which is what makes channel sharding bit-exact).
    """
    import jax.numpy as jnp

    B = hb.shape[0]
    need = (n_out + B) * R
    T = x.shape[0]
    if need > T:
        x = jnp.pad(x, ((0, need - T), (0, 0)))
    xr = x[:need].reshape(n_out + B, R, x.shape[1])
    u = jnp.einsum("mrc,br->bmc", xr, hb)
    y = jnp.zeros((n_out, x.shape[1]), x.dtype)
    for b in range(B):
        y = y + u[b, b : b + n_out]
    return y


def _block_taps(h: np.ndarray, R: int) -> np.ndarray:
    L = len(h)
    B = -(-L // R)
    hp = np.zeros(B * R, np.float32)
    hp[:L] = h
    return hp.reshape(B, R)


def _stage_counts(plan: CascadePlan, n_out: int) -> list[int]:
    """Required output count per stage: a stage producing n outputs
    with B tap-frames consumes (n + B) * R input samples."""
    counts = [n_out]
    for R, h in reversed(plan.stages[1:]):
        counts.append((counts[-1] + (-(-len(h) // R))) * R)
    counts.reverse()
    return counts


def cascade_input_need(plan: CascadePlan, n_out: int) -> int:
    """Input rows the cascade minimally consumes to emit ``n_out``
    outputs (after the delay pre-shift), with every stage on the XLA
    path: the first stage's ``(count + B) * R``. Pallas-aware sizing
    (grid rounding included) is :func:`chain_layout`'s ``rows``."""
    counts = _stage_counts(plan, int(n_out))
    R0, h0 = plan.stages[0]
    B0 = -(-len(h0) // int(R0))
    return (counts[0] + B0) * int(R0)


def _pallas_stage_ok(k: int, R: int, n_ch: int, n_frames: int) -> bool:
    """Pallas only for stages that are big enough to matter: small
    stages measure slower under the kernel (grid overheads dominate)
    AND their grid rounding — the kernel's quantum is ``_KB`` output
    frames (``_P`` parallel ``_SB``-frame sub-blocks per step) —
    inflates every upstream stage's output count through the chain
    layout. Thresholds from the v5e measurements behind BENCH_r04:
    >= 2^24 elements touched and a full first grid step. Taps must
    also fit the kernel's sub-block; very long single-stage plans
    (possible via the public design API) take the XLA polyphase path
    instead of erroring.

    ``TPUDAS_PALLAS_MIN_ELEMS`` overrides the element threshold so a
    measured crossover (``tools/retune_stage_ok.py``) can be applied
    on a live chip without a code edit — and without a process
    restart: callers key their caches on :func:`knob_fingerprint`."""
    import os

    from tpudas.ops.pallas_fir import _SB, kernel_quantum

    raw = os.environ.get("TPUDAS_PALLAS_MIN_ELEMS", "").strip()
    min_elems = int(raw) if raw else (1 << 24)
    return (
        k * R * n_ch >= min_elems
        and k >= kernel_quantum()
        and n_frames <= _SB
    )


def resolve_cascade_engine(engine: str = "auto") -> str:
    """'auto' -> 'pallas' on TPU backends, 'xla' elsewhere."""
    if engine == "auto":
        import jax

        return "pallas" if jax.default_backend() in ("tpu", "axon") else "xla"
    return engine


def chain_layout(
    plan: CascadePlan, n_out: int, n_ch: int, engine: str = "auto"
):
    """Per-stage execution layout: ``((engine_i, k_i), ...), rows``.

    ``k_i`` is the output count stage ``i`` emits and ``engine_i`` the
    kernel it runs under ('pallas'/'xla'); ``rows`` is the exact input
    length the first stage consumes. Sized back to front so every
    stage's input is exactly what its predecessor emits: an input of
    exactly ``rows`` flows through the whole cascade with ZERO internal
    padding (an internal ``jnp.pad`` materializes a full copy — a
    whole extra HBM round-trip at the full-rate stage). Shorter inputs
    still work (stages zero-pad, same numerics), they just pay the
    copy. This is also the single source of truth for which engine
    each stage actually runs (LFProc observability, the bench)."""
    engine = resolve_cascade_engine(engine)
    shapes = tuple(
        (int(R), -(-len(h) // int(R))) for R, h in plan.stages
    )
    return _layout_for(
        shapes, int(n_out), int(n_ch), engine, knob_fingerprint()
    )


def stage_engines(
    plan: CascadePlan, n_out: int, n_ch: int, engine: str = "auto"
) -> list[str]:
    """Which engine each stage will actually run under — the same
    decision :func:`_build_cascade_fn` makes at trace time, exposed so
    callers (LFProc observability, the bench) can report ground truth
    instead of the configured intent."""
    return [e for e, _ in chain_layout(plan, n_out, n_ch, engine)[0]]


def _check_quantized(x, qscale):
    """Shared guard for every quantized-ingest entry point: ``qscale``
    must accompany exactly an int16 payload."""
    import jax.numpy as jnp

    if qscale is not None and x.dtype != jnp.int16:
        raise ValueError(f"qscale given but data dtype is {x.dtype}")


def shift_to_phase(x, phase: int, delay: int, axis: int = 0):
    """Align a stream so causal cascade output ``k`` lands on
    zero-phase full-rate index ``phase + k*ratio``: drop
    ``phase - delay`` leading rows, or left-pad when the requested
    phase precedes the filter delay.  Single source for every cascade
    entry point (single-device, time-sharded, window-batched)."""
    import jax.numpy as jnp

    shift = int(phase) - int(delay)
    if shift >= 0:
        idx = (slice(None),) * axis + (slice(shift, None),)
        return x[idx]
    pad = [(0, 0)] * x.ndim
    pad[axis] = (-shift, 0)
    return jnp.pad(x, pad)


def _apply_cascade_stages(x, blocked, n_out, use_pallas, interpret,
                          qscale=None):
    """Traceable cascade body shared by the jit path and the shard_map
    (mesh) paths: x (T_local, C_local) -> (n_out, C_local).

    Per-stage engine/size decisions come from :func:`chain_layout` on
    the traced shape, so emitted sizes line up stage to stage (pad-free
    when the input is pre-sized to the layout's ``rows``).

    ``qscale`` (a TRACED scalar — one compiled executable serves every
    scale) marks a quantized int16 ingest window: the first stage
    reads the raw int16 payload (half the HBM bytes) and dequantizes
    inside its kernel.  On the XLA path that is a fused cast*scale —
    bit-identical to decoding first.  On the Pallas path the kernel
    casts raw in VMEM and, the FIR being linear, the scale multiplies
    the stage's decimated (R-times smaller) output.
    """
    import jax.numpy as jnp

    engine = "pallas" if use_pallas else "xla"
    layout, _rows = _layout_for(
        tuple((int(R), int(hb.shape[0])) for R, hb in blocked),
        int(n_out),
        int(x.shape[1]),
        engine,
        knob_fingerprint(),
    )
    first_pallas = layout[0][0] == "pallas" if layout else False
    quantized = qscale is not None and x.dtype == jnp.int16
    scale0 = None
    if quantized:
        if first_pallas:
            scale0 = jnp.float32(qscale)  # applied to stage-0 output
        else:
            x = x.astype(jnp.float32) * jnp.float32(qscale)
    else:
        x = x.astype(jnp.float32)
    for i, ((R, hb), (eng, k)) in enumerate(zip(blocked, layout)):
        if eng == "pallas":
            from tpudas.ops.pallas_fir import fir_decimate_pallas

            x = fir_decimate_pallas(x, hb, R, n_out=k, interpret=interpret)
        else:
            x = _polyphase_stage_xla(x, hb, R, k)
        if i == 0 and scale0 is not None:
            x = x * scale0
    return x


@functools.lru_cache(maxsize=256)
def _layout_for(stage_shapes, n_out, n_ch, engine, knobs=()):
    """chain_layout core on hashable (R, B) pairs: returns
    ``(((engine_i, k_i), ...), rows)``.  ``knobs`` is the env
    fingerprint (:func:`knob_fingerprint`) — unused in the body (the
    threshold/quantum reads go to the live env) but REQUIRED in the
    cache key so a mid-process retune recomputes the layout."""
    from tpudas.ops.pallas_fir import stage_input_rows

    k = int(n_out)
    ks: list = [None] * len(stage_shapes)
    for i in range(len(stage_shapes) - 1, -1, -1):
        R, B = stage_shapes[i]
        use = engine == "pallas" and _pallas_stage_ok(k, R, n_ch, B)
        ks[i] = ("pallas" if use else "xla", k)
        k = stage_input_rows(B, R, k) if use else (k + B) * R
    return tuple(ks), k


def _blocked_taps(plan: CascadePlan):
    """Frame-blocked taps as HOST numpy arrays: the apply body may be
    traced inside an outer jit (e.g. a benchmark step), and a device
    constant created during one trace must not be cached into another
    (UnexpectedTracerError) — numpy constants are staged per-trace."""
    return [(R, _block_taps(np.asarray(h), R)) for R, h in plan.stages]


def _clear_cascade_caches():
    """Drop every compiled-cascade cache (single-device, streaming,
    fused, time-sharded, window-batched) so the next call retraces.
    Env knob changes no longer need this — every cache keys on
    :func:`knob_fingerprint` — but benches/tests that monkeypatch
    resolution functions themselves still do."""
    _build_cascade_fn.cache_clear()
    _build_stream_cascade_fn.cache_clear()
    _build_fused_stream_fn.cache_clear()
    _build_stacked_stream_fn.cache_clear()
    _layout_for.cache_clear()
    try:
        from tpudas.parallel.pipeline import _build_sharded_cascade_fn

        _build_sharded_cascade_fn.cache_clear()
    except Exception:
        pass
    try:
        from tpudas.parallel.batch import _build_batched_cascade_fn

        _build_batched_cascade_fn.cache_clear()
    except Exception:
        pass


def _pallas_interpret() -> bool:
    # interpret mode off-TPU so the same code path is testable on
    # the CPU mesh (SURVEY.md §4 "distributed-without-a-cluster")
    import jax

    return jax.default_backend() not in ("tpu", "axon")


@functools.lru_cache(maxsize=64)
def _build_cascade_fn(plan: CascadePlan, n_out: int, engine: str, mesh=None,
                      ch_axis="ch", quantized=False, knobs=()):
    """jit-compiled causal cascade: x (T, C) -> (n_out, C); with
    ``quantized`` the signature is (x_int16, scale) and the scale is a
    TRACED operand (the compile caches on the bool, not the value —
    spools with differing quantization scales share one executable).

    With ``mesh``, the cascade runs under ``shard_map`` with channels
    split over the mesh's ``ch_axis`` — the zero-communication layout
    (SURVEY.md §2.4): every stage is channel-independent, so each
    device runs the full cascade (including the Pallas kernel, which
    GSPMD could not partition through a plain jit) on its local
    channel block.
    """
    import jax

    blocked = _blocked_taps(plan)
    use_pallas = engine == "pallas"
    interpret = _pallas_interpret() if use_pallas else False

    if quantized:
        def fn(x, scale):
            return _apply_cascade_stages(
                x, blocked, n_out, use_pallas, interpret, qscale=scale
            )
    else:
        def fn(x):
            return _apply_cascade_stages(
                x, blocked, n_out, use_pallas, interpret
            )

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map

        spec = P(None, ch_axis)
        in_specs = (spec, P()) if quantized else (spec,)
        body = shard_map(
            fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=spec,
            check_vma=False,
        )
        return jax.jit(body)
    return jax.jit(fn)


def cascade_decimate(
    x, plan: CascadePlan, phase: int, n_out: int, engine="auto",
    mesh=None, ch_axis="ch", qscale=None,
):
    """Zero-phase filtered + decimated samples of ``x`` (T, C).

    Output ``k`` equals the composite zero-phase filter of ``x``
    evaluated at full-rate index ``phase + k*plan.ratio`` — exactly the
    samples the reference's ``pass_filter → interpolate`` pipeline
    (lf_das.py:223-225) lands on when the target grid is sample-aligned.
    ``phase`` may be any non-negative int; edge regions (within
    ``plan.delay`` of either end) carry the usual truncation artifacts,
    which the overlap-save scheduler trims (SURVEY.md §3.1).

    With ``mesh``, channels are split over the mesh's ``ch_axis``
    (zero-communication sharding; C is zero-padded to a multiple of the
    axis size and trimmed after).

    ``qscale`` accepts a raw int16 quantized window (tdas ingest fast
    path): the H2D transfer and the first stage's HBM read stay int16
    and dequantization happens inside the first kernel — equivalent to
    ``cascade_decimate(x.astype(f32) * qscale, ...)``.  The scale is a
    traced operand: windows with different scales share one compile.
    """
    import jax.numpy as jnp

    from tpudas.obs.trace import span

    engine = resolve_cascade_engine(engine)
    x = jnp.asarray(x)
    _check_quantized(x, qscale)
    quantized = qscale is not None
    x2 = shift_to_phase(x, phase, plan.delay)
    args = (x2, jnp.float32(qscale)) if quantized else (x2,)
    if mesh is None:
        fn = _build_cascade_fn(
            plan, int(n_out), engine, quantized=quantized,
            knobs=knob_fingerprint(),
        )
        # dispatch-side timing (async backends sync at the caller's
        # np.asarray; the synced wall lands in window device metrics)
        with span("op.cascade", rows=int(x.shape[0]), engine=engine):
            return fn(*args)
    nc = mesh.shape[ch_axis]
    C = x2.shape[1]
    pad_c = -C % nc
    if pad_c:
        x2 = jnp.pad(x2, ((0, 0), (0, pad_c)))
        args = (x2, *args[1:])
    fn = _build_cascade_fn(plan, int(n_out), engine, mesh, ch_axis,
                           quantized=quantized, knobs=knob_fingerprint())
    out = fn(*args)
    return out[:, :C] if pad_c else out


# ---------------------------------------------------------------------------
# stateful streaming: carry per-stage filter state across blocks
#
# The batch entry points above re-derive every output from a window
# that includes the filter's full edge support — a caller processing a
# live stream must therefore re-read ~2x the edge of FULL-RATE data
# per round just to rebuild transient state it already computed.  The
# streaming form below instead carries each stage's trailing input
# samples as an explicit O(1) pytree: every input sample flows through
# every stage exactly once.
#
# Semantics (the contract tests/test_stream_state.py pins): feed the
# stream in blocks whose length is a multiple of ``plan.ratio``.  With
# ``X`` the concatenation of everything fed so far, the concatenated
# outputs satisfy
#
#     y_stream[m] == cascade_decimate(X, plan, phase=plan.delay, .)[m - W]
#
# for m >= W := stream_warmup_outputs(plan) — i.e. after the warm-up
# (the first W outputs read the zero-initialized carry and are
# discarded by callers), streamed output m is the zero-phase filtered
# value of the stream at full-rate index (m - W) * ratio + delay, and
# every kept output reads only samples that have already arrived (the
# emission lag past an output's center is exactly the filter's causal
# support, delay full-rate samples).
#
# Per-stage carry: stage i keeps its last P_i input samples, with
# P_i >= len(taps_i) - R_i so each new block's outputs have their full
# look-back.  The composite full-rate lag D = sum_i P_i * prod_{j<i}
# R_j telescopes to receptive_field - ratio at the minimal sizes;
# stage 0's carry absorbs the padding that rounds D up to a multiple
# of ratio so the streamed grid stays on the decimated grid
# (W = D / ratio).


@functools.lru_cache(maxsize=256)
def stream_carry_sizes(plan: CascadePlan) -> tuple:
    """Per-stage carried trailing samples (at each stage's own input
    rate).  Stage 0 includes the alignment pad that makes the composite
    lag a whole number of output samples."""
    sizes = [max(len(h) - int(R), 0) for R, h in plan.stages]
    d = 0
    prod = 1
    for p, (R, _h) in zip(sizes, plan.stages):
        d += p * prod
        prod *= int(R)
    sizes[0] += (-d) % plan.ratio
    return tuple(sizes)


def stream_warmup_outputs(plan: CascadePlan) -> int:
    """Outputs to discard after a zero-initialized carry (the composite
    stream lag in output samples)."""
    d = 0
    prod = 1
    for p, (R, _h) in zip(stream_carry_sizes(plan), plan.stages):
        d += p * prod
        prod *= int(R)
    assert d % plan.ratio == 0
    return d // plan.ratio


def cascade_stream_init(plan: CascadePlan, n_ch: int) -> tuple:
    """Zero carry pytree for :func:`cascade_decimate_stream`."""
    return tuple(
        np.zeros((p, int(n_ch)), np.float32)
        for p in stream_carry_sizes(plan)
    )


def _stream_stage_pallas(plan: CascadePlan, T: int, n_ch: int,
                         engine: str) -> tuple:
    """Static per-stage engine decisions for a stream block of T
    full-rate rows (True = the Pallas kernel runs that stage).

    Gated on ``TPUDAS_STREAM_PALLAS=1`` (off by default, read at
    build time): a stream block's carry-extended input is never the
    kernel's exact ``stage_input_rows`` sizing, so every Pallas stage
    would pay the internal pad's full input copy per block — whether
    that still beats the XLA formulation at stream block sizes is a
    measure-on-silicon question, and until it is measured the stream
    step stays on the proven path.  The batch entry points are
    unaffected."""
    import os

    if os.environ.get("TPUDAS_STREAM_PALLAS", "0") != "1":
        return tuple(False for _ in plan.stages)
    use = []
    t = int(T)
    for R, h in plan.stages:
        k = t // int(R)
        b = -(-len(h) // int(R))
        use.append(
            engine == "pallas" and _pallas_stage_ok(k, int(R), n_ch, b)
        )
        t = k
    return tuple(use)


def stream_stage_engines(plan: CascadePlan, T: int, n_ch: int,
                         engine: str = "auto") -> list:
    """Ground truth of which engine each stage runs under for a stream
    block of ``T`` rows — the streaming analogue of
    :func:`stage_engines` (same observability contract).  Under a
    fused variant every stage runs inside the one fused kernel, so
    every entry is the variant name."""
    engine = resolve_stream_engine(engine, plan, T, n_ch)
    if engine.startswith("fused"):
        return [engine for _ in plan.stages]
    return [
        "pallas" if u else "xla"
        for u in _stream_stage_pallas(plan, T, n_ch, engine)
    ]


# ---------------------------------------------------------------------------
# fused streaming (ISSUE 10): the whole cascade as ONE kernel.
#
# The per-stage stream step above materializes every stage's output in
# HBM before the next stage consumes it — at 10k channels that is
# ~T/R0 * C * 4 bytes written AND re-read per block for stage 1 alone.
# The carry is an SSM-style O(1) autoregressive cache (PAPERS.md
# "Compiler-First State Space Duality"), and the fused formulation
# treats it as one: a single scan (XLA) or Pallas grid walk keeps
# EVERY stage's trailing-sample state live across chunk steps and
# emits only the final decimated output.  Per-stage intermediates
# exist only at chunk granularity — sized to stay cache/VMEM-resident
# — so the full-rate input is read once and nothing else at full rate
# touches HBM.
#
# The carry pytree layout is IDENTICAL to the per-stage engines
# (stream_carry_sizes), so a stream can cross between
# cascade <-> fused mid-run (tests/test_fused.py pins resume in both
# directions), and the fused-XLA scan replays the per-stage
# arithmetic chunk-by-chunk — byte-identical outputs AND carry.


def fused_min_elems() -> int:
    """Block elements (T*C) below which a ``fused`` request falls back
    to the per-stage chain: per-chunk scan/grid overheads dominate on
    small blocks.  Default from the measured CPU crossover
    (tools/retune_stage_ok.py --fused, PERF.md §11);
    ``TPUDAS_FUSED_MIN_ELEMS`` applies a retune live (the dispatch
    caches key on :func:`knob_fingerprint`)."""
    import os

    raw = os.environ.get("TPUDAS_FUSED_MIN_ELEMS", "").strip()
    return int(raw) if raw else (1 << 23)


def fused_chunk_outputs(plan: CascadePlan, n_out: int) -> int:
    """Output samples per fused chunk step: the largest divisor of the
    block's output count not exceeding the target
    (``TPUDAS_FUSED_CHUNK``, default sized so one full-rate chunk is
    ~8192 rows — small enough that every stage's chunk intermediate
    stays cache/VMEM resident, large enough that per-chunk overhead
    amortizes).  A divisor (not a remainder split) keeps the scan a
    single static shape."""
    import os

    raw = os.environ.get("TPUDAS_FUSED_CHUNK", "").strip()
    target = int(raw) if raw else max(1, 8192 // plan.ratio)
    n_out = int(n_out)
    target = max(1, min(target, n_out))
    best = 1
    for d in range(1, n_out + 1):
        if d > target:
            break
        if n_out % d == 0:
            best = d
    return best


def fused_intermediate_bytes(plan: CascadePlan, T: int, n_ch: int) -> int:
    """HBM-traffic proxy: bytes of per-stage intermediates the
    per-stage chain materializes for a ``(T, n_ch)`` block that the
    fused formulation never writes (each is also re-READ by the next
    stage, so the eliminated traffic is ~2x this)."""
    rows = int(T)
    total = 0
    for R, _h in plan.stages[:-1]:
        rows //= int(R)
        total += rows * int(n_ch) * 4
    return total


def resolve_stream_engine(engine: str, plan: CascadePlan = None,
                          T: int = 0, n_ch: int = 0) -> str:
    """Resolve a stream-dispatch engine literal to what actually runs:
    ``auto`` -> the per-stage chain with backend routing; ``fused`` ->
    ``fused-pallas`` on TPU backends / ``fused-xla`` elsewhere when
    the block clears :func:`fused_min_elems` and the plan fits the
    kernel, else the per-stage chain (the measured-crossover
    threshold, same contract as ``_pallas_stage_ok``); explicit
    ``fused-xla``/``fused-pallas`` are forced."""
    if engine not in STREAM_ENGINES:
        raise ValueError(
            f"stream engine must be one of {STREAM_ENGINES}, got "
            f"{engine!r}"
        )
    if engine in ("auto", "pallas", "xla"):
        return resolve_cascade_engine(engine)
    if engine == "fused":
        if plan is not None and int(T) * int(n_ch) < fused_min_elems():
            return resolve_cascade_engine("auto")
        import jax

        engine = (
            "fused-pallas"
            if jax.default_backend() in ("tpu", "axon")
            else "fused-xla"
        )
    if engine == "fused-pallas" and plan is not None:
        from tpudas.ops.pallas_fir import fused_taps_fit

        chunk = fused_chunk_outputs(
            plan, max(int(T) // plan.ratio, 1)
        )
        if not fused_taps_fit(plan.stages, chunk):
            return "fused-xla"
    return engine


@functools.lru_cache(maxsize=128)
def _build_fused_stream_fn(plan: CascadePlan, T: int, n_ch: int,
                           variant: str, mesh=None, ch_axis="ch",
                           knobs=(), quantized=False):
    """jit-compiled FUSED stateful step: (x (T, C), carry) ->
    (y (T/ratio, C), new_carry) with every stage state threaded
    through one program — no per-stage HBM intermediates.

    ``variant`` is ``fused-xla`` (a ``lax.scan`` over chunk steps
    whose body replays the per-stage polyphase arithmetic — chunk
    intermediates live in the scan body, and outputs/carry are
    byte-identical to the per-stage chain) or ``fused-pallas`` (the
    pallas_fir v3 kernel: stage tails in VMEM scratch across the
    block's grid steps).  Donation, mesh wrapping, and the sharded
    carry contract mirror :func:`_build_stream_cascade_fn`; ``knobs``
    keys the cache on the live env fingerprint.

    ``quantized`` compiles the raw-int16 ingest variant: the step
    takes a traced ``qscale`` scalar and the dequantizing
    ``cast * scale`` is the program's first op (the stream analogue
    of the batch path's in-kernel dequant) — the block crosses H2D
    and is read from HBM as int16, half the bytes, no host-side f32
    copy.  Carry leaves stay float32, so the quantized and float
    variants share one carry layout (resume/crossover-safe)."""
    import jax
    import jax.numpy as jnp

    blocked = _blocked_taps(plan)
    sizes = stream_carry_sizes(plan)
    n_out_total = T // plan.ratio
    chunk_out = fused_chunk_outputs(plan, n_out_total)
    chunk_in = chunk_out * plan.ratio
    n_steps = n_out_total // chunk_out

    if variant == "fused-pallas":
        from tpudas.ops.pallas_fir import fused_cascade_pallas

        stages_np = tuple(
            (int(R), np.asarray(h, np.float32)) for R, h in plan.stages
        )
        interpret = _pallas_interpret()

        def core(x, carry):
            return fused_cascade_pallas(
                x, tuple(carry), stages_np, sizes,
                chunk_out, interpret=interpret,
            )

    else:

        def step(bufs, xc):
            y = xc
            new = []
            for (R, hb), p, buf in zip(blocked, sizes, bufs):
                xi = jnp.concatenate([buf, y], axis=0) if p else y
                k = y.shape[0] // R
                new.append(xi[xi.shape[0] - p:])
                y = _polyphase_stage_xla(xi, hb, R, k)
            return tuple(new), y

        def core(x, carry):
            if n_steps <= 1:
                bufs, y = step(tuple(carry), x)
                return y, bufs
            xs = x.reshape(n_steps, chunk_in, x.shape[1])
            bufs, ys = jax.lax.scan(step, tuple(carry), xs)
            return ys.reshape(n_out_total, x.shape[1]), bufs

    if quantized:
        def fn(x, carry, qscale):
            return core(x.astype(jnp.float32) * qscale, carry)
    else:
        def fn(x, carry):
            return core(x.astype(jnp.float32), carry)

    body = fn
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map

        spec = P(None, ch_axis)
        carry_specs = tuple(spec for _ in sizes)
        in_specs = (
            (spec, carry_specs, P()) if quantized
            else (spec, carry_specs)
        )
        body = shard_map(
            fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(spec, carry_specs),
            check_vma=False,
        )
    donate = (0, 1) if jax.default_backend() not in ("cpu",) else ()
    return jax.jit(body, donate_argnums=donate)


def _count_fused(plan: CascadePlan, T: int, n_ch: int,
                 variant: str) -> None:
    """Per-dispatch fused-path observability: rounds by variant and
    the HBM-traffic proxy (intermediate bytes the per-stage chain
    would have materialized) — tools/kernel_bench.py reads both."""
    from tpudas.obs.registry import get_registry

    reg = get_registry()
    reg.counter(
        "tpudas_fir_fused_rounds_total",
        "fused cascade stream steps dispatched",
        labelnames=("engine",),
    ).inc(engine=variant)
    reg.counter(
        "tpudas_fir_fused_intermediate_bytes_saved_total",
        "per-stage full-rate HBM intermediate bytes the fused kernel "
        "did not materialize",
    ).inc(fused_intermediate_bytes(plan, T, n_ch))


@functools.lru_cache(maxsize=128)
def _build_stream_cascade_fn(plan: CascadePlan, T: int, n_ch: int,
                             engine: str, mesh=None, ch_axis="ch",
                             knobs=(), quantized=False):
    """jit-compiled stateful step: (x (T, C), carry) -> (y (T/ratio, C),
    new_carry).  Both the input block and the carry are donated on
    accelerator backends — every buffer fed in is dead the moment the
    step returns, so steady-state streaming neither double-buffers the
    carry update nor holds the consumed input block in HBM.

    With ``mesh``, the step runs under ``shard_map`` with channels
    split over the mesh's ``ch_axis`` — the zero-communication layout:
    every stage (and its carry leaf) is channel-independent, so each
    device runs the identical per-stage loop on its local channel
    block and the sharded output/carry are byte-identical to the
    single-device step.  ``n_ch`` is then the PADDED global channel
    count (a multiple of the shard count; see
    tpudas.parallel.sharding's pad-and-mask layout)."""
    import jax
    import jax.numpy as jnp

    blocked = _blocked_taps(plan)
    sizes = stream_carry_sizes(plan)
    # Pallas thresholds see what one device actually traces: the
    # LOCAL channel count under a mesh
    n_ch_local = (
        n_ch // int(mesh.shape[ch_axis]) if mesh is not None else n_ch
    )
    use_pallas = _stream_stage_pallas(plan, T, n_ch_local, engine)
    interpret = _pallas_interpret() if any(use_pallas) else False

    def core(x, carry):
        new_carry = []
        for (R, hb), p, pall, buf in zip(blocked, sizes, use_pallas, carry):
            xc = jnp.concatenate([buf, x], axis=0) if p else x
            k = x.shape[0] // R
            if pall:
                from tpudas.ops.pallas_fir import fir_decimate_pallas

                y = fir_decimate_pallas(
                    xc, hb, R, n_out=k, interpret=interpret
                )
            else:
                y = _polyphase_stage_xla(xc, hb, R, k)
            new_carry.append(xc[xc.shape[0] - p:])
            x = y
        return x, tuple(new_carry)

    if quantized:
        # raw-int16 ingest variant: the dequantizing cast * scale is
        # the first traced op (in-kernel dequant — the batch path's
        # contract), with the scale a traced scalar so every window
        # scale shares one compile
        def fn(x, carry, qscale):
            return core(x.astype(jnp.float32) * qscale, carry)
    else:
        def fn(x, carry):
            return core(x.astype(jnp.float32), carry)

    body = fn
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map

        spec = P(None, ch_axis)
        carry_specs = tuple(spec for _ in sizes)
        in_specs = (
            (spec, carry_specs, P()) if quantized
            else (spec, carry_specs)
        )
        body = shard_map(
            fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(spec, carry_specs),
            check_vma=False,
        )
    donate = (0, 1) if jax.default_backend() not in ("cpu",) else ()
    return jax.jit(body, donate_argnums=donate)


def cascade_decimate_stream(x, carry, plan: CascadePlan, engine="auto",
                            mesh=None, ch_axis="ch", qscale=None):
    """One stateful streaming step of the cascade.

    x: (T, C) float32 block, T a multiple of ``plan.ratio``; ``carry``
    from :func:`cascade_stream_init` or a previous step.  Returns
    ``(y (T/ratio, C), new_carry)`` — see the streamed-output contract
    in the section comment above.  Neither the previous carry nor the
    input block may be reused after the call (both are donated on
    accelerators).

    With ``mesh``, channels are split over the mesh's ``ch_axis``
    (zero-communication shard_map; pad-and-mask for non-divisible
    counts) and the returned carry leaves are SHARDED device arrays —
    feed them back verbatim and they stay resident on the mesh with no
    host round-trip; ``y`` is trimmed to the logical channel count.
    The sharded step is byte-identical to the single-device step
    (channel columns are independent; tests/test_parallel.py pins it).

    ``engine`` accepts every :data:`STREAM_ENGINES` literal: the
    per-stage chain (``auto``/``pallas``/``xla``) or the fused
    single-kernel formulations (``fused`` resolves by backend and the
    measured size threshold; ``fused-xla``/``fused-pallas`` force a
    variant).  The carry layout is shared, so the engine may change
    between steps of one stream (cascade <-> fused crossover).

    ``qscale`` accepts a raw int16 quantized block (tdas ingest fast
    path): the H2D transfer and the first stage's HBM read stay int16
    and dequantization happens inside the step — bit-identical to
    feeding ``x.astype(f32) * qscale``.  The scale is a traced
    operand (one compile serves every scale); the carry stays float32
    either way.
    """
    import jax.numpy as jnp

    _check_quantized(x, qscale)
    quantized = qscale is not None
    T = int(np.shape(x)[0])
    n_ch = int(np.shape(x)[1])
    # size thresholds see what one device actually traces: the LOCAL
    # channel count under a mesh (same contract as _pallas_stage_ok)
    n_ch_res = (
        n_ch if mesh is None
        else -(-n_ch // int(mesh.shape[ch_axis]))
    )
    engine = resolve_stream_engine(engine, plan, T, n_ch_res)
    fused = engine.startswith("fused")
    x = jnp.asarray(x) if mesh is None else x
    if T % plan.ratio:
        raise ValueError(
            f"stream block length {T} is not a multiple of the "
            f"decimation ratio {plan.ratio}"
        )
    sizes = stream_carry_sizes(plan)
    if len(carry) != len(sizes) or any(
        int(np.shape(b)[0]) != p for b, p in zip(carry, sizes)
    ):
        raise ValueError(
            "carry does not match this plan's stream_carry_sizes "
            f"({[int(np.shape(b)[0]) for b in carry]} vs {list(sizes)})"
        )
    from tpudas.obs import devprof
    from tpudas.obs.trace import span

    knobs = knob_fingerprint()
    if mesh is None:
        if fused:
            fn = _build_fused_stream_fn(plan, T, n_ch, engine,
                                        knobs=knobs, quantized=quantized)
            sp = span("fir.fused", rows=T, engine=engine)
        else:
            fn = _build_stream_cascade_fn(plan, T, n_ch, engine,
                                          knobs=knobs, quantized=quantized)
            sp = span("op.cascade_stream", rows=T, engine=engine)
        shape_key = (T, n_ch, engine, int(quantized), _plan_tag(plan))
        devprof.note_kernel("cascade", shape_key, knobs)
        args = (jnp.float32(qscale),) if quantized else ()
        bufs = tuple(jnp.asarray(b, jnp.float32) for b in carry)
        cost = devprof.kernel_cost(
            "cascade", shape_key, fn, (x, bufs) + args
        )
        t0 = _time.perf_counter()
        with sp:
            out = fn(x, bufs, *args)
        devprof.note_launch(engine, t0, out, cost=cost)
        if fused:
            _count_fused(plan, T, n_ch, engine)
        return out
    from tpudas.parallel.sharding import (
        channel_pad,
        place_block,
        place_carry_leaves,
    )

    C = n_ch
    Cp = C + channel_pad(C, mesh, ch_axis)
    if any(int(np.shape(b)[1]) not in (C, Cp) for b in carry):
        raise ValueError(
            f"stream carry channel width {[np.shape(b) for b in carry]} "
            f"matches neither the block ({C}) nor the padded shard "
            f"layout ({Cp})"
        )
    xs = place_block(x, mesh, ch_axis, keep_dtype=quantized)
    if any(int(np.shape(b)[1]) != Cp for b in carry):
        # first call after open/resume: the leaves are host arrays at
        # the logical width — pad-and-place them once; every later
        # round feeds back the sharded leaves this step returns
        carry = place_carry_leaves(carry, mesh, ch_axis)
    if fused:
        fn = _build_fused_stream_fn(plan, T, Cp, engine, mesh, ch_axis,
                                    knobs=knobs, quantized=quantized)
        sp = span("fir.fused", rows=T, engine=engine,
                  shards=int(mesh.shape[ch_axis]))
    else:
        fn = _build_stream_cascade_fn(plan, T, Cp, engine, mesh, ch_axis,
                                      knobs=knobs, quantized=quantized)
        sp = span("op.cascade_stream", rows=T, engine=engine,
                  shards=int(mesh.shape[ch_axis]))
    shape_key = (
        T, Cp, engine, int(quantized), _plan_tag(plan),
        int(mesh.shape[ch_axis]),
    )
    devprof.note_kernel("cascade", shape_key, knobs)
    args = (jnp.float32(qscale),) if quantized else ()
    cost = devprof.kernel_cost(
        "cascade", shape_key, fn, (xs, tuple(carry)) + args
    )
    t0 = _time.perf_counter()
    with sp:
        y, bufs = fn(xs, tuple(carry), *args)
    devprof.note_launch(engine, t0, (y, bufs), cost=cost)
    if fused:
        _count_fused(plan, T, C, engine)
    return (y[:, :C] if Cp != C else y), bufs


# ---------------------------------------------------------------------------
# ragged-stacked streaming (ISSUE 16): N same-plan streams as ONE
# device program.
#
# Every stage of the cascade is channel-column independent (the
# property the PR 7 pad-and-mask layout already relies on), so N
# streams' (T, C_i) blocks concatenated along the channel axis run the
# SAME per-stage arithmetic in one launch and each stream's columns
# come out byte-identical to its solo step.  The ragged packing is the
# static (width, offset) row list: offsets are cumulative widths, the
# split slices are compiled into the program, and each stream's carry
# leaves are sliced back out as separate device arrays — a member
# leaving its batch group keeps a carry indistinguishable from solo
# execution.  With a mesh the stacked width is pad-and-masked to the
# shard multiple INSIDE the program (zeros are inert, exactly as in
# tpudas.parallel.sharding), so a 2-D stream x channel layout composes
# with the PR 7 mesh.


@functools.lru_cache(maxsize=128)
def _build_stacked_stream_fn(plan: CascadePlan, T: int, widths: tuple,
                             engine: str, mesh=None, ch_axis="ch",
                             knobs=(), quantized=False):
    """jit-compiled STACKED stateful step: (N blocks (T, C_i), N
    carries) -> (N outputs (T/ratio, C_i), N new carries), all inside
    one device program.  ``engine`` is a resolved
    :data:`STACKED_ENGINES` literal: ``xla`` replays the per-stage
    chain of :func:`_build_stream_cascade_fn`, ``fused-xla`` the
    chunked ``lax.scan`` of :func:`_build_fused_stream_fn` — both on
    the concatenated (T, sum C_i) block, so per-stream outputs AND
    carry leaves are byte-identical to the solo step (channel columns
    are independent).  ``quantized`` takes a traced ``qscale`` scalar
    shared by every member (the batch group former keys on it).
    Inputs are donated on accelerator backends, mirroring the solo
    builders."""
    import jax
    import jax.numpy as jnp

    blocked = _blocked_taps(plan)
    sizes = stream_carry_sizes(plan)
    widths = tuple(int(w) for w in widths)
    C = sum(widths)
    offsets = tuple(int(o) for o in np.cumsum((0,) + widths[:-1]))

    if engine == "fused-xla":
        n_out_total = T // plan.ratio
        chunk_out = fused_chunk_outputs(plan, n_out_total)
        chunk_in = chunk_out * plan.ratio
        n_steps = n_out_total // chunk_out

        def step(bufs, xc):
            y = xc
            new = []
            for (R, hb), p, buf in zip(blocked, sizes, bufs):
                xi = jnp.concatenate([buf, y], axis=0) if p else y
                k = y.shape[0] // R
                new.append(xi[xi.shape[0] - p:])
                y = _polyphase_stage_xla(xi, hb, R, k)
            return tuple(new), y

        def core(x, carry):
            if n_steps <= 1:
                bufs, y = step(tuple(carry), x)
                return y, bufs
            xs = x.reshape(n_steps, chunk_in, x.shape[1])
            bufs, ys = jax.lax.scan(step, tuple(carry), xs)
            return ys.reshape(n_out_total, x.shape[1]), bufs

    else:

        def core(x, carry):
            new_carry = []
            for (R, hb), p, buf in zip(blocked, sizes, carry):
                xc = jnp.concatenate([buf, x], axis=0) if p else x
                k = x.shape[0] // R
                y = _polyphase_stage_xla(xc, hb, R, k)
                new_carry.append(xc[xc.shape[0] - p:])
                x = y
            return x, tuple(new_carry)

    body = core
    Cp = C
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map

        Cp = C + (-C % int(mesh.shape[ch_axis]))
        spec = P(None, ch_axis)
        carry_specs = tuple(spec for _ in sizes)
        body = shard_map(
            core,
            mesh=mesh,
            in_specs=(spec, carry_specs),
            out_specs=(spec, carry_specs),
            check_vma=False,
        )
    pad = Cp - C

    def fn(xs, carries, *args):
        # ragged channel packing: concatenate member columns at the
        # static offsets, run one program, slice members back out
        x = jnp.concatenate(list(xs), axis=1).astype(jnp.float32)
        if quantized:
            x = x * args[0]
        cat = tuple(
            jnp.concatenate(
                [c[i].astype(jnp.float32) for c in carries], axis=1
            )
            for i in range(len(sizes))
        )
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
            cat = tuple(jnp.pad(b, ((0, 0), (0, pad))) for b in cat)
        y, new = body(x, cat)
        outs = tuple(
            y[:, o:o + w] for o, w in zip(offsets, widths)
        )
        new_carries = tuple(
            tuple(leaf[:, o:o + w] for leaf in new)
            for o, w in zip(offsets, widths)
        )
        return outs, new_carries

    donate = (0, 1) if jax.default_backend() not in ("cpu",) else ()
    return jax.jit(fn, donate_argnums=donate)


def cascade_decimate_stream_stacked(blocks, carries, plan: CascadePlan,
                                    engine="xla", mesh=None,
                                    ch_axis="ch", qscale=None):
    """N same-plan streams' stateful steps as ONE stacked device
    program (the ragged-batched fleet path, ISSUE 16).

    ``blocks`` is a sequence of (T, C_i) blocks sharing T (mixed
    channel widths are the ragged case — each stream keeps its own
    width); ``carries`` the matching per-stream carry pytrees (from
    :func:`cascade_stream_init` or previous solo/stacked steps — the
    layouts are identical, so a stream moves freely between solo and
    stacked execution).  Returns ``[(y_i, new_carry_i), ...]`` in
    member order; every output and carry leaf is byte-identical to
    what ``cascade_decimate_stream`` returns for that member alone
    (channel-column independence — the same property that makes the
    PR 7 sharded step byte-identical).

    ``engine`` must be a RESOLVED :data:`STACKED_ENGINES` literal —
    callers resolve per member at the member's own solo width first
    (see tpudas.fleet.batch), so stacking never changes an engine
    decision.  ``qscale`` is a single traced scalar shared by every
    member: mixed-scale streams must not be stacked together (the
    group former keys on the scale value).  Neither the blocks nor
    the previous carries may be reused after the call (donated on
    accelerator backends)."""
    import jax.numpy as jnp

    if engine not in STACKED_ENGINES:
        raise ValueError(
            f"stacked engine must be one of {STACKED_ENGINES}, got "
            f"{engine!r}"
        )
    blocks = tuple(blocks)
    carries = tuple(tuple(c) for c in carries)
    if not blocks or len(blocks) != len(carries):
        raise ValueError(
            f"blocks/carries length mismatch: {len(blocks)} vs "
            f"{len(carries)}"
        )
    T = int(np.shape(blocks[0])[0])
    if T % plan.ratio:
        raise ValueError(
            f"stream block length {T} is not a multiple of the "
            f"decimation ratio {plan.ratio}"
        )
    widths = tuple(int(np.shape(b)[1]) for b in blocks)
    sizes = stream_carry_sizes(plan)
    for i, (b, c, w) in enumerate(zip(blocks, carries, widths)):
        if int(np.shape(b)[0]) != T:
            raise ValueError(
                f"member {i} block has {int(np.shape(b)[0])} rows; the "
                f"stacked step needs a shared T={T} (partition waves "
                "by block length)"
            )
        if len(c) != len(sizes) or any(
            int(np.shape(leaf)[0]) != p for leaf, p in zip(c, sizes)
        ):
            raise ValueError(
                f"member {i} carry does not match this plan's "
                "stream_carry_sizes "
                f"({[int(np.shape(leaf)[0]) for leaf in c]} vs "
                f"{list(sizes)})"
            )
        if any(int(np.shape(leaf)[1]) != w for leaf, _p in zip(c, sizes)):
            raise ValueError(
                f"member {i} carry width "
                f"{[np.shape(leaf) for leaf in c]} does not match its "
                f"block width {w}"
            )
        _check_quantized(b, qscale)
    quantized = qscale is not None
    knobs = knob_fingerprint()
    fn = _build_stacked_stream_fn(
        plan, T, widths, engine, mesh, ch_axis,
        knobs=knobs, quantized=quantized,
    )
    from tpudas.obs import devprof
    from tpudas.obs.trace import span

    shape_key = (T, widths, engine, int(quantized), _plan_tag(plan))
    devprof.note_kernel("cascade_stacked", shape_key, knobs)
    args = (jnp.float32(qscale),) if quantized else ()
    cost = devprof.kernel_cost(
        "cascade_stacked", shape_key, fn, (blocks, carries) + args
    )
    t0 = _time.perf_counter()
    with span("op.stacked", rows=T, streams=len(blocks), engine=engine):
        outs, news = fn(blocks, carries, *args)
    devprof.note_launch(engine, t0, (outs, news), cost=cost,
                        stacked=True)
    if engine == "fused-xla":
        for w in widths:
            _count_fused(plan, T, w, engine)
    return list(zip(outs, news))


# ---------------------------------------------------------------------------
# probing (host-side, analytic)


def impulse_response(plan: CascadePlan, n: int | None = None) -> np.ndarray:
    """Composite full-rate impulse response of the cascade (numpy).

    Equivalent to pushing a unit impulse through all stages WITHOUT
    decimation (valid because decimation commutes with the linear
    filters for response-support analysis) — the analytic counterpart of
    the reference's synthetic-impulse probe (lf_das.py:47-87).
    """
    h = np.ones(1, np.float64)
    prod = 1
    for R, taps in plan.stages:
        up = np.zeros(prod * (len(taps) - 1) + 1, np.float64)
        up[::prod] = np.asarray(taps, np.float64)
        h = np.convolve(h, up)
        prod *= R
    if n is not None and len(h) < n:
        h = np.pad(h, (0, n - len(h)))
    return h


@functools.lru_cache(maxsize=256)
def edge_support_samples(plan: CascadePlan, tol: float = 1e-3) -> int:
    """One-sided support (full-rate samples) of the composite impulse
    response thresholded at ``max*tol`` — the cascade's equivalent of
    ``get_edge_effect_time`` (reference lf_das.py:67-77)."""
    h = impulse_response(plan)
    mag = np.abs(h)
    above = np.nonzero(mag > mag.max() * tol)[0]
    center = plan.delay
    return int(max(center - above[0], above[-1] - center, 0))
