"""Zero-phase band filtering on TPU.

The reference's ``pass_filter`` is scipy's forward-backward IIR
(``sosfiltfilt``) — inherently sequential, a poor fit for TPU. The
TPU-native equivalent used here exploits the fact that filtfilt's
magnitude response is exactly ``|H(f)|^2`` with zero phase: we apply the
squared Butterworth magnitude directly in the frequency domain —
``rfft → multiply → irfft`` along the time axis, batched over channels.
This is O(T log T) per channel (vs O(T·order) sequential), maps onto
XLA's fused FFT, and matches ``sosfiltfilt`` numerics away from chunk
edges; the self-calibrating edge probe (tpudas.proc.edge, reference
lf_das.py:47-87) measures the *actual* impulse-response support of this
filter, so the overlap-save scheduler trims exactly the right halo.

Reference call sites: lf_das.py:40 (probe pipeline, corner = 0.4/dt)
and lf_das.py:223 (engine, corner = 0.45/dt low-pass).
"""

from __future__ import annotations

import functools
import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from tpudas.ops.fftlen import next_tpu_fft_len

from tpudas.core import units as _units

__all__ = [
    "patch_pass_filter",
    "fft_lowpass_response",
    "fft_pass_filter",
    "fft_stream_init",
    "fft_pass_filter_stream",
    "fft_pass_filter_stream_stacked",
]


def _butter_mag2(freqs, low, high, order):
    """Squared Butterworth magnitude response (filtfilt-equivalent).

    ``low``/``high`` are the band edges in the same units as ``freqs``
    (low = high-pass corner, high = low-pass corner, as in
    ``pass_filter(time=(low, high))``).
    """
    resp = jnp.ones_like(freqs)
    if high is not None:
        resp = resp / (1.0 + (freqs / high) ** (2 * order))
    if low is not None:
        safe = jnp.maximum(freqs, jnp.finfo(freqs.dtype).tiny)
        resp = resp / (1.0 + (low / safe) ** (2 * order))
        resp = jnp.where(freqs <= 0.0, 0.0, resp)
    return resp


@functools.partial(
    jax.jit, static_argnames=("nfft", "order", "has_low", "has_high")
)
def _fft_filter_kernel(data, d_sec, low, high, nfft, order, has_low, has_high):
    """data: (T, C) float32; filter along axis 0. Returns (T, C)."""
    n = data.shape[0]
    spec = jnp.fft.rfft(data, n=nfft, axis=0)
    freqs = jnp.arange(nfft // 2 + 1, dtype=jnp.float32) / (nfft * d_sec)
    resp = _butter_mag2(
        freqs,
        low if has_low else None,
        high if has_high else None,
        order,
    )
    out = jnp.fft.irfft(spec * resp[:, None], n=nfft, axis=0)
    return out[:n].astype(data.dtype)


def fft_pass_filter(data, d_sec, low=None, high=None, order=4):
    """Apply the zero-phase band filter along axis 0 of a (T, C) array.

    Pure jittable entry point (also used by bench / graft entry).
    """
    data = jnp.asarray(data, jnp.float32)
    squeeze = data.ndim == 1
    if squeeze:
        data = data[:, None]
    nfft = next_tpu_fft_len(int(data.shape[0]))
    out = _fft_filter_kernel(
        data,
        jnp.float32(d_sec),
        jnp.float32(0.0 if low is None else low),
        jnp.float32(0.0 if high is None else high),
        nfft,
        int(order),
        low is not None,
        high is not None,
    )
    return out[:, 0] if squeeze else out


def fft_lowpass_response(nfft, d_sec, corner, order=4):
    """The rfft-domain response used by the kernel (for composition into
    fused pipelines, e.g. tpudas.parallel.pipeline)."""
    freqs = jnp.arange(nfft // 2 + 1, dtype=jnp.float32) / (nfft * d_sec)
    return _butter_mag2(freqs, None, jnp.float32(corner), order)


# ---------------------------------------------------------------------------
# streaming overlap-save: carry the filter's edge support across blocks
#
# The batch entry point above re-filters a window that includes the
# edge support on both sides; a streaming caller would have to re-read
# that halo every block.  The carry below is the overlap-save state —
# the last ``2 * edge`` RAW input samples — so each input sample enters
# the FFT engine exactly once and the emitted region of every block is
# clean (full ``edge`` support on both sides, circular-wrap artifacts
# confined to the discarded halo) as long as ``edge`` covers the
# filter's impulse-response support at the engine's tolerance (the
# same contract the batch overlap-save scheduler enforces through
# tpudas.proc.edge).


def fft_stream_init(edge: int, n_ch: int) -> np.ndarray:
    """Zero carry for :func:`fft_pass_filter_stream`: the stream's last
    ``2 * edge`` input samples (zeros = silence before the stream)."""
    return np.zeros((2 * int(edge), int(n_ch)), np.float32)


@functools.lru_cache(maxsize=128)
def _build_fft_stream_fn(T, rows_carry, n_ch, d_sec, low, high, order,
                         mesh, ch_axis, quantized=False):
    """jit-compiled FFT stream step: (block (T, C), carry (2*edge, C))
    -> (filtered (T, C), new_carry).  Both inputs are donated on
    accelerator backends (the caller never reuses either).

    With ``mesh``, the step runs under ``shard_map`` with channels
    split over ``ch_axis`` — the filter is column-independent (one
    rfft/irfft batch per channel), so each device runs the identical
    kernel on its local channel block and the sharded result is
    byte-identical to the single-device step.  ``n_ch`` is then the
    PADDED global channel count (tpudas.parallel.sharding's
    pad-and-mask layout).

    ``quantized`` compiles the raw-int16 ingest variant: the step
    takes a traced ``qscale`` scalar and the dequantizing
    ``cast * scale`` on the block is the program's first op (the
    overlap-save carry stays float32 — the layouts match the float
    variant's, so resume and mid-stream payload changes are safe)."""
    edge = rows_carry // 2

    def core(block, carry):
        xc = jnp.concatenate(
            [carry.astype(jnp.float32), block], axis=0,
        )
        filt = fft_pass_filter(xc, d_sec, low=low, high=high, order=order)
        return filt[edge : edge + T], xc[xc.shape[0] - 2 * edge :]

    if quantized:
        def fn(block, carry, qscale):
            return core(block.astype(jnp.float32) * qscale, carry)
    else:
        def fn(block, carry):
            return core(block.astype(jnp.float32), carry)

    body = fn
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map

        spec = P(None, ch_axis)
        in_specs = (spec, spec, P()) if quantized else (spec, spec)
        body = shard_map(
            fn, mesh=mesh, in_specs=in_specs,
            out_specs=(spec, spec), check_vma=False,
        )
    donate = (0, 1) if jax.default_backend() not in ("cpu",) else ()
    return jax.jit(body, donate_argnums=donate)


def fft_pass_filter_stream(block, carry, d_sec, low=None, high=None,
                           order=4, mesh=None, ch_axis="ch",
                           qscale=None):
    """One streaming step of the zero-phase FFT band filter.

    block: (T, C) new input samples; carry: (2*edge, C) from
    :func:`fft_stream_init` or a previous step.  Returns
    ``(filtered, new_carry)`` where ``filtered[i]`` is the zero-phase
    filtered value of the stream at the position ``edge`` samples
    BEHIND ``block[i]`` — the emission lags the input by ``edge``
    samples (an output needs its right-side support before it can be
    clean).  With a zero-initialized carry the first ``edge`` emitted
    samples read pre-stream silence; callers discard them exactly as
    the batch path discards its stream-start edge.

    Neither the block nor the previous carry may be reused after the
    call: both are DONATED on accelerator backends (the returned
    carry replaces the old one — feed it back verbatim and it stays
    device-resident with no host round-trip).

    With ``mesh``, channels are split over the mesh's ``ch_axis``
    (zero-communication shard_map; pad-and-mask for non-divisible
    counts) and the returned carry is a SHARDED device array — feed it
    back verbatim and it stays resident on the mesh with no host
    round-trip; ``filtered`` is trimmed to the logical channel count.
    Byte-identical to the single-device step (the filter is
    column-independent).

    ``qscale`` accepts a raw int16 quantized block (tdas ingest fast
    path): the H2D transfer stays int16 and dequantization happens
    inside the step — bit-identical to feeding
    ``block.astype(f32) * qscale``; the scale is a traced operand."""
    from tpudas.ops.fir import _check_quantized

    _check_quantized(block, qscale)
    quantized = qscale is not None
    rows_carry = int(np.shape(carry)[0])
    if len(np.shape(carry)) != 2 or rows_carry % 2:
        raise ValueError(
            f"carry must be (2*edge, C), got {tuple(np.shape(carry))}"
        )
    T = int(np.shape(block)[0])
    from tpudas.obs import devprof
    from tpudas.obs.trace import span

    edge = rows_carry // 2
    args = (jnp.float32(qscale),) if quantized else ()
    if mesh is None:
        carry = jnp.asarray(carry, jnp.float32)
        block = jnp.asarray(block)  # int16 stays int16 across H2D
        if not quantized:
            block = block.astype(jnp.float32)
        if block.ndim != 2 or block.shape[1] != carry.shape[1]:
            raise ValueError(
                f"block {tuple(block.shape)} does not match carry "
                f"{tuple(carry.shape)}"
            )
        fn = _build_fft_stream_fn(
            T, rows_carry, int(block.shape[1]),
            float(d_sec), low, high, int(order), None, ch_axis,
            quantized=quantized,
        )
        shape_key = (
            T, rows_carry, int(block.shape[1]), float(d_sec), low,
            high, int(order), int(quantized),
        )
        devprof.note_kernel("fft", shape_key, ())
        cost = devprof.kernel_cost(
            "fft", shape_key, fn, (block, carry) + args
        )
        t0 = _time.perf_counter()
        with span("op.fft_stream", rows=T, edge=edge):
            out = fn(block, carry, *args)
        devprof.note_launch("fft", t0, out, cost=cost)
        return out
    from tpudas.parallel.sharding import channel_pad, place_block

    C = int(np.shape(block)[1])
    C_carry = int(np.shape(carry)[1])
    Cp = C + channel_pad(C, mesh, ch_axis)
    if C_carry not in (C, Cp):
        raise ValueError(
            f"block {(T, C)} does not match carry "
            f"{tuple(np.shape(carry))}"
        )
    xs = place_block(block, mesh, ch_axis, keep_dtype=quantized)
    if C_carry != Cp:
        # first call after open/resume: the carry is a host array at
        # the logical width — pad-and-place it once; every later step
        # feeds back the sharded carry this step returns
        carry = place_block(np.asarray(carry, np.float32), mesh, ch_axis)
    fn = _build_fft_stream_fn(
        T, rows_carry, Cp, float(d_sec), low, high, int(order),
        mesh, ch_axis, quantized=quantized,
    )
    shape_key = (
        T, rows_carry, Cp, float(d_sec), low, high, int(order),
        int(quantized), int(mesh.shape[ch_axis]),
    )
    devprof.note_kernel("fft", shape_key, ())
    cost = devprof.kernel_cost("fft", shape_key, fn, (xs, carry) + args)
    t0 = _time.perf_counter()
    with span(
        "op.fft_stream", rows=T, edge=edge,
        shards=int(mesh.shape[ch_axis]),
    ):
        out, new_carry = fn(xs, carry, *args)
    devprof.note_launch("fft", t0, (out, new_carry), cost=cost)
    return (out[:, :C] if Cp != C else out), new_carry


@functools.lru_cache(maxsize=128)
def _build_stacked_fft_fn(T, rows_carry, widths, d_sec, low, high, order,
                          mesh, ch_axis, quantized=False):
    """jit-compiled STACKED FFT stream step (the ragged-batched fleet
    path, ISSUE 16): N same-parameter streams' overlap-save steps run
    as ONE device program on the channel-concatenated (T, sum C_i)
    block.  The filter is column-independent (one rfft/irfft batch per
    channel, nfft a function of T only), so each member's filtered
    block and new carry come out byte-identical to its solo
    :func:`fft_pass_filter_stream` step; members are sliced back out
    at the static ragged (width, offset) rows.  With ``mesh`` the
    stacked width is pad-and-masked to the shard multiple inside the
    program (zeros are inert).  Inputs are donated on accelerator
    backends, mirroring the solo builder."""
    edge = rows_carry // 2
    widths = tuple(int(w) for w in widths)
    C = sum(widths)
    offsets = tuple(int(o) for o in np.cumsum((0,) + widths[:-1]))

    def core(block, carry):
        xc = jnp.concatenate(
            [carry.astype(jnp.float32), block], axis=0,
        )
        filt = fft_pass_filter(xc, d_sec, low=low, high=high, order=order)
        return filt[edge : edge + T], xc[xc.shape[0] - 2 * edge :]

    body = core
    Cp = C
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from tpudas.parallel.compat import shard_map

        Cp = C + (-C % int(mesh.shape[ch_axis]))
        spec = P(None, ch_axis)
        body = shard_map(
            core, mesh=mesh, in_specs=(spec, spec),
            out_specs=(spec, spec), check_vma=False,
        )
    pad = Cp - C

    def fn(blocks, carries, *args):
        x = jnp.concatenate(list(blocks), axis=1).astype(jnp.float32)
        if quantized:
            x = x * args[0]
        cat = jnp.concatenate(
            [c.astype(jnp.float32) for c in carries], axis=1
        )
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
            cat = jnp.pad(cat, ((0, 0), (0, pad)))
        filt, new = body(x, cat)
        outs = tuple(
            filt[:, o:o + w] for o, w in zip(offsets, widths)
        )
        news = tuple(
            new[:, o:o + w] for o, w in zip(offsets, widths)
        )
        return outs, news

    donate = (0, 1) if jax.default_backend() not in ("cpu",) else ()
    return jax.jit(fn, donate_argnums=donate)


def fft_pass_filter_stream_stacked(blocks, carries, d_sec, low=None,
                                   high=None, order=4, mesh=None,
                                   ch_axis="ch", qscale=None):
    """N streams' overlap-save FFT filter steps as ONE stacked device
    program.  ``blocks`` share T and the filter parameters; each keeps
    its own channel width (ragged packing).  Returns
    ``[(filtered_i, new_carry_i), ...]`` in member order,
    byte-identical per member to :func:`fft_pass_filter_stream` (the
    filter is column-independent).  ``qscale`` is one traced scalar
    shared by every member — the fleet group former keys on the value,
    so mixed-scale streams are never stacked.  Blocks and previous
    carries are donated on accelerator backends — do not reuse."""
    from tpudas.ops.fir import _check_quantized

    blocks = tuple(blocks)
    carries = tuple(carries)
    if not blocks or len(blocks) != len(carries):
        raise ValueError(
            f"blocks/carries length mismatch: {len(blocks)} vs "
            f"{len(carries)}"
        )
    T = int(np.shape(blocks[0])[0])
    rows_carry = int(np.shape(carries[0])[0])
    if rows_carry % 2:
        raise ValueError(
            f"carry must be (2*edge, C), got {tuple(np.shape(carries[0]))}"
        )
    for i, (b, c) in enumerate(zip(blocks, carries)):
        if int(np.shape(b)[0]) != T or int(np.shape(c)[0]) != rows_carry:
            raise ValueError(
                f"member {i} shapes {tuple(np.shape(b))}/"
                f"{tuple(np.shape(c))} do not match the wave's "
                f"T={T}, 2*edge={rows_carry}"
            )
        if int(np.shape(b)[1]) != int(np.shape(c)[1]):
            raise ValueError(
                f"member {i} block {tuple(np.shape(b))} does not match "
                f"carry {tuple(np.shape(c))}"
            )
        _check_quantized(b, qscale)
    quantized = qscale is not None
    widths = tuple(int(np.shape(b)[1]) for b in blocks)
    fn = _build_stacked_fft_fn(
        T, rows_carry, widths, float(d_sec), low, high, int(order),
        mesh, ch_axis, quantized=quantized,
    )
    from tpudas.obs import devprof
    from tpudas.obs.trace import span

    shape_key = (
        T, rows_carry, widths, float(d_sec), low, high, int(order),
        int(quantized),
    )
    devprof.note_kernel("fft_stacked", shape_key, ())
    args = (jnp.float32(qscale),) if quantized else ()
    cost = devprof.kernel_cost(
        "fft_stacked", shape_key, fn, (blocks, carries) + args
    )
    t0 = _time.perf_counter()
    with span(
        "op.stacked", rows=T, streams=len(blocks), edge=rows_carry // 2,
    ):
        outs, news = fn(blocks, carries, *args)
    devprof.note_launch("fft", t0, (outs, news), cost=cost,
                        stacked=True)
    return list(zip(outs, news))


def _host_sosfiltfilt(data, d_sec, low, high, order):
    """Host reference engine: scipy Butterworth + sosfiltfilt (the
    reference's exact numerics)."""
    from scipy.signal import butter, sosfiltfilt

    nyq = 0.5 / d_sec
    if low is not None and high is not None:
        sos = butter(order, [low / nyq, high / nyq], btype="bandpass", output="sos")
    elif high is not None:
        sos = butter(order, high / nyq, btype="lowpass", output="sos")
    elif low is not None:
        sos = butter(order, low / nyq, btype="highpass", output="sos")
    else:
        return np.asarray(data, np.float64)
    return sosfiltfilt(sos, np.asarray(data, np.float64), axis=0)


def patch_pass_filter(patch, order=4, engine=None, **kwargs):
    """Patch-level ``pass_filter(time=(low, high))``.

    Exactly one named dimension must be given; band edges are in Hz for
    time (cycles per meter for distance). ``None`` bounds are open.
    """
    if len(kwargs) != 1:
        raise ValueError("pass_filter requires exactly one dim, e.g. time=(None, 5)")
    (dim, band), = kwargs.items()
    low, high = band
    low = _units.get_seconds(low) if low is not None else None
    high = _units.get_seconds(high) if high is not None else None
    ax = patch.axis_of(dim)
    d = patch.get_sample_step(dim)
    if d is None or d <= 0:
        raise ValueError(f"cannot infer sample step for dim {dim!r}")
    nyq = 0.5 / d
    for edge in (low, high):
        if edge is not None and not (0 < edge <= nyq):
            raise ValueError(
                f"filter corner {edge} Hz outside (0, Nyquist={nyq}]"
            )

    from tpudas.obs.trace import span

    data = patch.data
    moved = ax != 0
    if engine in ("numpy", "scipy", "host"):
        with span("op.pass_filter", engine="host"):
            host = np.asarray(data)
            if moved:
                host = np.moveaxis(host, ax, 0)
            out = _host_sosfiltfilt(host, d, low, high, order)
            out = out.astype(np.asarray(data).dtype, copy=False)
            if moved:
                out = np.moveaxis(out, 0, ax)
    else:
        with span("op.pass_filter", engine="fft"):
            arr = jnp.asarray(data)
            if moved:
                arr = jnp.moveaxis(arr, ax, 0)
            out = fft_pass_filter(arr, d, low=low, high=high, order=order)
            if moved:
                out = jnp.moveaxis(out, 0, ax)
    return patch.new(data=out)
