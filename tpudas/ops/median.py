"""Sliding-window median despike.

TPU-native equivalent of the notebook's direct
``scipy.ndimage.median_filter`` calls (low_pass_dascore.ipynb:265,:334):
1-D (per-trace) or square 2-D footprints with reflect boundaries. The
device kernel gathers the w (or w*w) shifted views and takes the middle
of a sorted stack — for the small despike windows used in the QC path
(5-9 taps) this is a handful of fused gathers + an O(w log w) sort on
the VPU, no host round trip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["median_filter", "patch_median_filter"]


def _reflect_pad_1d(arr, pad, axis):
    # scipy.ndimage default mode is 'reflect' ((c b a | a b c | c b a))
    idx_front = jnp.arange(pad - 1, -1, -1)
    idx_back = jnp.arange(arr.shape[axis] - 1, arr.shape[axis] - pad - 1, -1)
    front = jnp.take(arr, idx_front, axis=axis)
    back = jnp.take(arr, idx_back, axis=axis)
    return jnp.concatenate([front, arr, back], axis=axis)


@functools.partial(jax.jit, static_argnames=("sizes", "axes"))
def _median_kernel(data, sizes, axes):
    padded = data
    for ax, sz in zip(axes, sizes):
        if sz > 1:
            padded = _reflect_pad_1d(padded, sz // 2, ax)
    views = []
    # gather all prod(sizes) shifted views
    shifts = [()]
    for sz in sizes:
        shifts = [sh + (k,) for sh in shifts for k in range(sz)]
    n_out = data.shape
    for sh in shifts:
        view = padded
        for ax, k in zip(axes, sh):
            view = jax.lax.slice_in_dim(view, k, k + n_out[ax], axis=ax)
        views.append(view)
    stack = jnp.stack(views, axis=0)
    return jnp.median(stack, axis=0).astype(data.dtype)


def median_filter(data, size, axes=None):
    """Median filter along ``axes`` (default all), matching
    ``scipy.ndimage.median_filter(x, size)`` semantics: ``size`` is a
    single odd footprint or a per-axis tuple (1 = no filtering on that
    axis, e.g. ``(3, 1)`` despikes along time only on a (T, C) array).
    """
    arr = jnp.asarray(data)
    if axes is None:
        axes = tuple(range(arr.ndim))
    axes = tuple(int(a) for a in axes)
    if np.isscalar(size):
        sizes = (int(size),) * len(axes)
    else:
        sizes = tuple(int(s) for s in size)
        if len(sizes) != len(axes):
            raise ValueError(
                f"size tuple {sizes} must have one entry per filtered "
                f"axis ({len(axes)})"
            )
    for sz in sizes:
        if sz % 2 != 1:
            raise ValueError("median filter sizes must be odd")
    return _median_kernel(arr, sizes, axes)


def patch_median_filter(patch, size=5, dim=None, engine=None):
    """Patch-level despike. ``dim=None`` filters over all dims (the
    notebook's 2-D usage); ``dim="time"`` filters per channel."""
    if engine in ("numpy", "host", "scipy"):
        from scipy.ndimage import median_filter as _scipy_mf

        host = np.asarray(patch.data)
        if dim is None:
            out = _scipy_mf(host, size=size)
        else:
            ax = patch.axis_of(dim)
            sz = [1] * host.ndim
            sz[ax] = size
            out = _scipy_mf(host, size=tuple(sz))
        return patch.new(data=out)
    axes = None if dim is None else (patch.axis_of(dim),)
    out = median_filter(patch.data, size, axes=axes)
    return patch.new(data=out)
