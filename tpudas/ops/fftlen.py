"""TPU-friendly FFT sizing.

XLA's TPU FFT handles 2/3/5-smooth lengths with a real FFT algorithm,
but falls back to a materialized DFT *matmul* for lengths with larger
prime factors — an O(n^2) memory blow-up (observed: a 182952-point FFT
attempting a 134 GB [n, n] allocation, because scipy's ``next_fast_len``
admits factors 7 and 11). All tpudas kernels therefore pad to the next
5-smooth length: bounded ~6% typical padding overhead, and the
frequency-domain response is length-aware so results are unchanged.
"""

from __future__ import annotations

__all__ = ["next_tpu_fft_len"]

_cache: dict[int, int] = {}


def _is_5smooth(n: int) -> bool:
    for p in (2, 3, 5):
        while n % p == 0:
            n //= p
    return n == 1


def next_tpu_fft_len(n: int) -> int:
    """Smallest 5-smooth (2^a * 3^b * 5^c) integer >= n."""
    n = int(n)
    if n <= 1:
        return 1
    hit = _cache.get(n)
    if hit is not None:
        return hit
    # search upward from n; 5-smooth numbers are dense enough (<6% gaps)
    m = n
    while not _is_5smooth(m):
        m += 1
    _cache[n] = m
    return m
