"""TPU compute kernels for Patch operations.

Every kernel has two engines:

- ``"jax"`` (default): jitted XLA/TPU path — static shapes, fused, and
  vmap/shard_map-friendly. This is the production path.
- ``"numpy"``: float64 host reference implementation used for parity
  testing and for the reference notebooks' explicit ``engine="numpy"``
  call sites.
"""

from tpudas.ops.filter import patch_pass_filter, fft_lowpass_response
from tpudas.ops.resample import patch_interpolate, interp_indices_weights
from tpudas.ops.rolling import PatchRoller
from tpudas.ops.median import patch_median_filter

__all__ = [
    "patch_pass_filter",
    "fft_lowpass_response",
    "patch_interpolate",
    "interp_indices_weights",
    "PatchRoller",
    "patch_median_filter",
]
