"""Resampling / interpolation kernels.

The reference decimates by linear interpolation onto a uniform target
grid (``Patch.interpolate(time=new_axis)``, lf_das.py:42, :223-225;
numpy/scipy C under DASCore). TPU-native design: datetimes and index
arithmetic stay on host in float64/int64 (exact), the device kernel is a
pure gather + lerp — two fused gathers, no data-dependent shapes, no
datetime math under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpudas.core.attrs import derive_coord_attrs
from tpudas.core.timeutils import to_datetime64, to_float_seconds

__all__ = ["patch_interpolate", "interp_indices_weights", "gather_lerp"]


def interp_indices_weights(src, dst):
    """Host-side: indices/weights for linear interp of ``dst`` into ``src``.

    Both axes may be datetime64 or numeric; computation is float64
    (datetime64 → int64 ns), exact for ms-quantized grids. Out-of-range
    targets clamp to the edge values (np.interp semantics, which the
    reference's engine inherits).

    Returns (idx int32 array, w float32 array) with
    ``out = src_data[idx] * (1-w) + src_data[idx+1] * w``.
    """
    src = np.asarray(src)
    dst = np.asarray(dst)
    if np.issubdtype(src.dtype, np.datetime64) or np.issubdtype(
        dst.dtype, np.datetime64
    ):
        epoch = to_datetime64(src[0])
        s = to_float_seconds(to_datetime64(src), epoch=epoch)
        d = to_float_seconds(to_datetime64(dst), epoch=epoch)
    else:
        s = src.astype(np.float64)
        d = dst.astype(np.float64)
    if s.size < 2:
        raise ValueError("need at least 2 source samples to interpolate")
    if np.any(np.diff(s) <= 0):
        raise ValueError("source axis must be strictly increasing")
    idx = np.searchsorted(s, d, side="right") - 1
    idx = np.clip(idx, 0, s.size - 2)
    denom = s[idx + 1] - s[idx]
    w = (d - s[idx]) / denom
    w = np.clip(w, 0.0, 1.0)  # edge clamp, matches np.interp
    return idx.astype(np.int32), w.astype(np.float32)


@jax.jit
def gather_lerp(data, idx, w):
    """Device kernel: linear interp along axis 0 of (T, C) data."""
    lo = jnp.take(data, idx, axis=0)
    hi = jnp.take(data, idx + 1, axis=0)
    wcol = w.reshape((-1,) + (1,) * (data.ndim - 1)).astype(data.dtype)
    return lo + (hi - lo) * wcol


def patch_interpolate(patch, engine=None, **kwargs):
    """Patch-level ``interpolate(dim=new_axis)`` (linear, edge-clamped)."""
    if len(kwargs) != 1:
        raise ValueError("interpolate requires exactly one dim, e.g. time=new_axis")
    (dim, new_axis), = kwargs.items()
    ax = patch.axis_of(dim)
    src = patch.coords[dim]
    if dim == "time":
        new_axis = to_datetime64(np.asarray(new_axis))
    else:
        new_axis = np.asarray(new_axis, dtype=np.float64)
    idx, w = interp_indices_weights(src, new_axis)

    data = patch.data
    moved = ax != 0
    if engine in ("numpy", "host"):
        host = np.asarray(data)
        if moved:
            host = np.moveaxis(host, ax, 0)
        lo = host[idx]
        hi = host[idx + 1]
        out = lo + (hi - lo) * w.astype(np.float64).reshape(
            (-1,) + (1,) * (host.ndim - 1)
        )
        out = out.astype(host.dtype, copy=False)
        if moved:
            out = np.moveaxis(out, 0, ax)
    else:
        arr = jnp.asarray(data)
        if moved:
            arr = jnp.moveaxis(arr, ax, 0)
        out = gather_lerp(arr, jnp.asarray(idx), jnp.asarray(w))
        if moved:
            out = jnp.moveaxis(out, 0, ax)

    coords = dict(patch.coords)
    coords[dim] = new_axis
    # refresh the step attr for the new axis; other attrs carry over
    attrs = patch.attrs.to_dict()
    attrs.update(derive_coord_attrs(coords, patch.dims))
    return patch.new(data=out, coords=coords, attrs=attrs)
