"""Drain-mode shard execution: the streaming engine, pointed backward.

One shard = one :class:`tpudas.fleet.engine.LowpassStreamRunner` over
the archive slice, with the realtime poll loop replaced by
drain-as-fast-as-possible: ``step()`` until ``terminate``, no poll
sleeps, the source slice capped by the runner's ``time_range`` and
each round bounded by ``ingest_limit_sec`` (so the shard lease is
renewed between rounds, never mid-unbounded-round).  Everything the
realtime path earned rides along unchanged — the per-round fault
boundary (transient retry with backoff, corrupt-file quarantine),
ENOSPC resource shedding, crc-stamped carry, startup integrity
audit — because it IS the realtime code path.

Failure policy per shard:

- transient/corrupt/resource failures: retried by the shard's own
  fault boundary exactly as a live stream would (the retry sleep
  renews the lease in bounded slices);
- :class:`~tpudas.backfill.queue.LeaseLostError` (another worker
  reclaimed a wedged-looking lease): the shard is abandoned
  mid-drain — the thief's execution is authoritative, this staging
  directory becomes an orphan for ``audit_backfill`` to sweep;
- fatal failures (config/programming errors, exhausted retries): the
  shard is **parked** in the queue (counted, fsck-able) and the
  worker moves to the next shard instead of dying;
- ``KeyboardInterrupt``/``SystemExit``/SIGKILL: crash-only — the
  worker just dies; its leases go stale and other workers reclaim.

:func:`run_worker` is the whole worker: claim → drain → commit,
looping until every shard is done or parked, then (optionally) race
the deterministic stitch — also commit-wins, so N workers may all
try.
"""

from __future__ import annotations

import os
import time as _time

import numpy as np

from tpudas.backfill.queue import BackfillQueue, Lease, LeaseLostError
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.resilience.faults import classify_failure
from tpudas.utils.logging import log_event

__all__ = ["execute_shard", "run_worker", "scrub_index_cache", "shard_spec"]


def scrub_index_cache(folder: str) -> None:
    """Remove the directory-index cache (and its ``.prev``) before a
    commit rename: the cache records absolute paths, which the rename
    invalidates — and the index is regenerable by construction, so
    readers of the committed directory simply rescan."""
    from tpudas.io.index import INDEX_FILENAME

    for name in (INDEX_FILENAME, INDEX_FILENAME + ".prev"):
        path = os.path.join(folder, name)
        try:
            os.remove(path)
        except FileNotFoundError:
            pass

# a retry backoff is slept in lease-renewable slices no longer than
# this, so a long transient backoff cannot let the lease expire
_RENEW_SLICE_SEC = 5.0


def shard_spec(plan: dict, shard: dict):
    """The :class:`~tpudas.fleet.config.StreamSpec` for one shard:
    the lowpass config rebuilt from the plan, ``start_time`` pulled
    back by the warm-up lead (grid-aligned, so the shard's decimation
    phase — and with it byte-identity inside ``[t0, t1)`` — matches
    the sequential run's)."""
    from tpudas.fleet.config import StreamConfig, StreamSpec

    cfg = dict(plan["config"])
    lead_ns = int(round(float(plan["lead_seconds"]) * 1e9))
    start_ns = max(int(shard["t0_ns"]) - lead_ns, int(plan["t0_ns"]))
    ops = cfg.get("detect_operators")
    if ops is not None:
        # JSON round-trips tuples to lists; the registry accepts both
        ops = tuple((name, dict(params)) for name, params in ops)
    config = StreamConfig(
        kind="lowpass",
        start_time=np.datetime64(start_ns, "ns"),
        output_sample_interval=cfg["output_sample_interval"],
        edge_buffer=cfg["edge_buffer"],
        process_patch_size=cfg["process_patch_size"],
        engine=cfg.get("engine"),
        distance=cfg.get("distance"),
        on_gap=cfg.get("on_gap"),
        filter_order=cfg.get("filter_order"),
        data_gap_tolerance=cfg.get("data_gap_tolerance"),
        # shards write output files + carry only; pyramid and detect
        # state are derived ONCE from the stitched rows (stitch.py) —
        # per-shard serve/detect state near a cold boundary would
        # diverge from the sequential run's
        pyramid=False,
        detect=False,
        detect_operators=ops,
        health=False,
        quarantine=True,
        stateful=True,
        poll_interval=0.0,
    )
    return StreamSpec(
        stream_id=shard["id"], source=plan["source"], config=config
    )


def _drain_cap_ns(plan: dict, shard: dict) -> int:
    """The input-slice cap: the shard end plus the tail lead (the
    stateful engine's emitted head trails its ingested head by
    warmup-minus-delay output steps, so the slice must extend past
    ``t1`` for the kept rows to reach it), clamped to the archive
    slice end."""
    tail_ns = int(round(float(plan["tail_seconds"]) * 1e9))
    return min(int(shard["t1_ns"]) + tail_ns, int(plan["t1_ns"]))


def execute_shard(
    queue: BackfillQueue, lease: Lease, sleep_fn=_time.sleep
) -> str:
    """Drain one claimed shard into its staging directory and commit.
    Returns ``"committed"`` | ``"lost"`` | ``"parked"``.  Raises
    :class:`LeaseLostError` when the lease is stolen mid-drain and
    lets ``KeyboardInterrupt``/``SystemExit`` propagate (crash-only).
    """
    from tpudas.fleet.engine import LowpassStreamRunner

    plan = queue.plan
    shard = queue.shard(lease.shard)
    staging = queue.staging_dir(lease)
    t_wall = _time.perf_counter()
    try:
        runner = LowpassStreamRunner(shard_spec(plan, shard), staging)
    except Exception as exc:
        # a shard that cannot even build its runner (config error) is
        # parked, not a worker death — mirrors the fleet's build-time
        # park
        log_event(
            "backfill_runner_build_failed",
            shard=lease.shard,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        queue.park(lease, exc, classify_failure(exc))
        return "parked"
    runner.time_range = (
        None, np.datetime64(_drain_cap_ns(plan, shard), "ns")
    )
    runner.ingest_limit_sec = plan.get("ingest_limit_sec")
    try:
        with span("backfill.shard", shard=lease.shard):
            while True:
                queue.renew(lease)
                res = runner.step()
                if res.status == "terminate":
                    break
                if res.status == "retry":
                    # sleep the boundary's backoff in lease-renewable
                    # slices — a 60 s transient backoff must not let
                    # the lease expire under us
                    remaining = float(res.delay)
                    while remaining > 0:
                        sleep_fn(min(remaining, _RENEW_SLICE_SEC))
                        remaining -= _RENEW_SLICE_SEC
                        queue.renew(lease)
            runner.finish()
    except LeaseLostError:
        raise
    except Exception as exc:
        kind = classify_failure(exc)
        log_event(
            "backfill_shard_failed",
            shard=lease.shard,
            kind=kind,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        runner.record_fatal(exc)
        queue.park(lease, exc, kind)
        return "parked"
    wall = _time.perf_counter() - t_wall
    get_registry().histogram(
        "tpudas_backfill_shard_seconds",
        "wall seconds to drain one shard (claim to commit)",
    ).observe(wall)
    scrub_index_cache(staging)
    # pre-commit verification: the staging directory must fsck clean
    # (it was audited at runner startup; a drain that left damage
    # behind must not become the authoritative shard)
    from tpudas.integrity.audit import audit

    report = audit(staging, repair=True)
    if not report["clean"]:
        err = RuntimeError(
            f"staging for {lease.shard} failed post-drain audit "
            f"({len(report['issues'])} issue(s))"
        )
        queue.park(lease, err, "corrupt")
        return "parked"
    return queue.commit(
        lease, staging,
        wall_s=round(wall, 4), rounds=int(runner.rounds),
    )


def run_worker(
    root,
    worker: str | None = None,
    stitch: bool = True,
    idle_poll: float = 0.25,
    max_wall: float | None = None,
    sleep_fn=_time.sleep,
    **queue_kwargs,
) -> dict:
    """One backfill worker, end to end: claim shards (reclaiming stale
    leases) until every shard is done or parked, then optionally race
    the stitch.  Returns the worker's tally.  ``max_wall`` bounds the
    loop for tests; production workers wait out other workers' leases
    (a dead worker's lease goes stale after ``lease_ttl``)."""
    queue = BackfillQueue(root, worker=worker, **queue_kwargs)
    tally = {
        "worker": queue.worker, "committed": 0, "adopted": 0,
        "lost": 0, "parked": 0, "stitched": False,
    }
    t0 = _time.perf_counter()
    while True:
        if max_wall is not None and _time.perf_counter() - t0 > max_wall:
            raise TimeoutError(
                f"backfill worker exceeded max_wall={max_wall}s "
                f"with queue counts {queue.counts()}"
            )
        lease = queue.claim_next()
        if lease is None:
            if queue.resolved():
                break
            sleep_fn(idle_poll)  # other workers hold live leases
            continue
        if os.path.isdir(queue.shard_dir(lease.shard)):
            # a crashed commit (rename landed, marker missing): adopt
            outcome = queue.adopt(lease)
            if outcome == "committed":
                tally["adopted"] += 1
            continue
        try:
            outcome = execute_shard(queue, lease, sleep_fn=sleep_fn)
        except LeaseLostError as exc:
            log_event(
                "backfill_lease_lost",
                shard=lease.shard,
                worker=queue.worker,
                error=str(exc)[:200],
            )
            continue
        tally[outcome] = tally.get(outcome, 0) + 1
    if stitch and queue.all_done():
        from tpudas.backfill.stitch import stitch_backfill

        result = stitch_backfill(root, queue=queue)
        tally["stitched"] = result["status"] in ("committed", "already")
        tally["stitch_status"] = result["status"]
    tally["counts"] = queue.counts()
    log_event("backfill_worker_done", **{
        k: v for k, v in tally.items() if k != "counts"
    })
    return tally
