"""tpudas.backfill — crash-only cluster backfill (shared FS or object store).

The batch half of the paper's workload (reprocess months of archived
spool with a new filter plan, new detect operators, or a codec
re-encode) executed by N concurrent worker processes/hosts with
exactly-once shard commit:

- :mod:`tpudas.backfill.queue` — the filesystem-backed work queue of
  time-shard jobs with crc-stamped manifests and lease-based claiming
  (stale leases are reclaimed by any worker; double execution resolves
  by the commit-wins atomic rename);
- :mod:`tpudas.backfill.runner` — drain-mode shard execution reusing
  :class:`tpudas.fleet.engine.LowpassStreamRunner` (poll loop replaced
  by drain-as-fast-as-possible over the slice) with the full fault
  ladder, ENOSPC shedding, and fatal-shard parking;
- :mod:`tpudas.backfill.stitch` — deterministic stitching of the
  committed shard outputs into a result byte-identical to a single
  sequential run (pyramid synced, detect ledger/scores recomputed
  chunk-invariantly);
- :mod:`tpudas.backfill.objqueue` — the same queue/worker/stitch over
  a :mod:`tpudas.store` object store: N hosts with NO shared
  filesystem, conditional-put leases and upload-manifest commits in
  place of atomic renames.

``tools/backfill_drill.py`` is the chaos harness (N workers, seeded
SIGKILLs, injected claim/commit faults); ``tools/backfill_bench.py``
records the worker-count scaling curve.  See RESILIENCE.md, "Cluster
backfill".
"""

from tpudas.backfill.objqueue import (  # noqa: F401
    StoreBackfillQueue,
    plan_backfill_store,
    run_store_worker,
    stitch_store_backfill,
)
from tpudas.backfill.queue import (  # noqa: F401
    BackfillQueue,
    Lease,
    LeaseLostError,
    build_plan,
    load_plan,
    plan_backfill,
)
from tpudas.backfill.runner import run_worker  # noqa: F401
from tpudas.backfill.stitch import stitch_backfill  # noqa: F401

__all__ = [
    "BackfillQueue",
    "Lease",
    "LeaseLostError",
    "StoreBackfillQueue",
    "build_plan",
    "load_plan",
    "plan_backfill",
    "plan_backfill_store",
    "run_store_worker",
    "run_worker",
    "stitch_backfill",
    "stitch_store_backfill",
]
