"""tpudas.backfill — crash-only cluster backfill over a shared filesystem.

The batch half of the paper's workload (reprocess months of archived
spool with a new filter plan, new detect operators, or a codec
re-encode) executed by N concurrent worker processes/hosts with
exactly-once shard commit:

- :mod:`tpudas.backfill.queue` — the filesystem-backed work queue of
  time-shard jobs with crc-stamped manifests and lease-based claiming
  (stale leases are reclaimed by any worker; double execution resolves
  by the commit-wins atomic rename);
- :mod:`tpudas.backfill.runner` — drain-mode shard execution reusing
  :class:`tpudas.fleet.engine.LowpassStreamRunner` (poll loop replaced
  by drain-as-fast-as-possible over the slice) with the full fault
  ladder, ENOSPC shedding, and fatal-shard parking;
- :mod:`tpudas.backfill.stitch` — deterministic stitching of the
  committed shard outputs into a result byte-identical to a single
  sequential run (pyramid synced, detect ledger/scores recomputed
  chunk-invariantly).

``tools/backfill_drill.py`` is the chaos harness (N workers, seeded
SIGKILLs, injected claim/commit faults); ``tools/backfill_bench.py``
records the worker-count scaling curve.  See RESILIENCE.md, "Cluster
backfill".
"""

from tpudas.backfill.queue import (  # noqa: F401
    BackfillQueue,
    Lease,
    LeaseLostError,
    load_plan,
    plan_backfill,
)
from tpudas.backfill.runner import run_worker  # noqa: F401
from tpudas.backfill.stitch import stitch_backfill  # noqa: F401

__all__ = [
    "BackfillQueue",
    "Lease",
    "LeaseLostError",
    "load_plan",
    "plan_backfill",
    "run_worker",
    "stitch_backfill",
]
