"""The object-store backfill queue: no shared filesystem anywhere.

Same job, same exactly-once guarantees, different substrate: N
workers on N hosts coordinate entirely through one object store —
conditional puts where the POSIX queue had atomic renames.  Layout
under one job prefix::

    <prefix>/backfill.json          # the plan: create-only put (immutable)
    <prefix>/leases/<id>.json       # CAS'd lease (claim/renew/steal)
    <prefix>/shards/<id>/<file>     # shard output objects (unconditional)
    <prefix>/shards/<id>/.shard.json# upload manifest: keys + digests,
                                    # uploaded AFTER every output object
    <prefix>/done/<id>.json         # create-only exactly-once marker
    <prefix>/parked/<id>.json       # create-only park record
    <prefix>/result/<file>          # the stitched result objects
    <prefix>/result.json            # the result's upload manifest
    <prefix>/result.done.json       # create-only stitch marker

How each POSIX mechanism translates:

**Claim/steal** was write-settle-reread (last write wins whole);
here it is strictly stronger: ``put_if(if_absent)`` to claim an open
shard, ``put_if(if_token=<stale lease's token>)`` to steal an
expired one — the store itself serializes racing claimers, and the
loser gets :class:`~tpudas.store.base.CASConflictError` instead of a
settle race.  **Renew** CASes the lease on the token read back, so a
renew racing a steal loses definitively
(:class:`~tpudas.backfill.queue.LeaseLostError`).

**Commit** was one atomic rename; an object store has no rename, so
the commit is a three-step upload protocol whose LAST step is the
atomic one: (1) put every staged output file under ``shards/<id>/``
— unconditional, because shard bytes are deterministic, so racing
executions write identical objects; (2) put ``.shard.json``, the
upload manifest naming every object and its content token — the
"directory is complete" signal a rename used to give for free;
(3) ``put_if(if_absent)`` the done marker — the single atomic event
that makes exactly one execution THE commit.  A conflict at (3) is
the commit-wins race, answered the same way as the rename version:
discard local staging, the winner's marker stands.

**Adoption** (crash inside the commit window): a shard with a
verifying ``.shard.json`` — every listed object present with its
listed token — but no done marker is adopted by writing the marker;
an upload manifest that does NOT verify means the crash was mid-step
(1)/(2) and the shard simply re-executes over the debris (uploads
are idempotent).  ``audit_backfill`` classifies the same states from
``list()`` + token verification — no directory walk.

Shard EXECUTION is untouched: each worker drains into a private
local scratch directory through the unmodified
:func:`tpudas.backfill.runner.execute_shard` (it duck-types the
queue), with all the realtime fault machinery riding along.  Only
coordination and durability moved off the filesystem.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time as _time

from tpudas.backfill.queue import (
    Lease,
    LeaseLostError,
    build_plan,
    _PLAN_VERSION,
)
from tpudas.integrity.checksum import (
    stamp_json,
    strip_stamp,
    verify_json_obj,
)
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.resilience.faults import fault_point
from tpudas.store.base import CASConflictError, ObjectNotFoundError
from tpudas.utils.logging import log_event

__all__ = [
    "SHARD_MANIFEST_NAME",
    "StoreBackfillQueue",
    "load_plan_store",
    "plan_backfill_store",
    "run_store_worker",
    "stitch_store_backfill",
]

PLAN_KEY = "backfill.json"
LEASES_PREFIX = "leases"
SHARDS_PREFIX = "shards"
DONE_PREFIX = "done"
PARKED_PREFIX = "parked"
RESULT_PREFIX = "result"
RESULT_MANIFEST_KEY = "result.json"
RESULT_DONE_KEY = "result.done.json"
SHARD_MANIFEST_NAME = ".shard.json"


def _dumps(obj: dict) -> bytes:
    """Stamped canonical bytes for a coordination object (same crc
    stamp discipline as every on-disk JSON artifact)."""
    return (json.dumps(stamp_json(obj), indent=1) + "\n").encode()


def _loads_verified(data: bytes):
    """``(payload, ok)`` — a torn/mismatched object protects nothing,
    exactly like a torn lease file never did."""
    try:
        obj = json.loads(data.decode())
    except (ValueError, AttributeError):
        return None, False
    status = verify_json_obj(obj)
    if status == "mismatch" or not isinstance(obj, dict):
        return None, False
    return strip_stamp(obj), True


def plan_backfill_store(store, prefix: str, source, t0, t1,
                        **kwargs) -> dict:
    """Plan one object-store backfill job: the pure
    :func:`~tpudas.backfill.queue.build_plan` persisted as a
    CREATE-ONLY object — the store's conditional put is what makes
    the plan immutable (a second planner gets the conflict, not a
    clobber)."""
    prefix = str(prefix).strip("/")
    plan = build_plan(source, t0, t1, **kwargs)
    key = f"{prefix}/{PLAN_KEY}" if prefix else PLAN_KEY
    try:
        store.put_if(key, _dumps(plan), if_absent=True)
    except CASConflictError:
        raise FileExistsError(
            f"object {key!r} already exists; a backfill plan is "
            "immutable (use a new prefix to re-plan)"
        ) from None
    get_registry().gauge(
        "tpudas_backfill_shards", "time shards in the backfill plan"
    ).set(len(plan["shards"]))
    log_event(
        "backfill_planned", root=f"store:{prefix}",
        shards=len(plan["shards"]),
        shard_seconds=plan["shard_seconds"],
        lead_seconds=plan["lead_seconds"],
        tail_seconds=plan["tail_seconds"],
    )
    return plan


def load_plan_store(store, prefix: str) -> dict:
    prefix = str(prefix).strip("/")
    key = f"{prefix}/{PLAN_KEY}" if prefix else PLAN_KEY
    data, _token = store.get(key)
    payload, ok = _loads_verified(data)
    if not ok:
        raise ValueError(f"backfill plan {key!r} failed its crc32 check")
    if int(payload.get("version", -1)) != _PLAN_VERSION:
        raise ValueError(
            f"unknown backfill plan version {payload.get('version')!r}"
        )
    return payload


class StoreBackfillQueue:
    """Lease/commit operations for one worker over one object-store
    backfill prefix.  Surface-compatible with
    :class:`~tpudas.backfill.queue.BackfillQueue` as far as
    :func:`~tpudas.backfill.runner.execute_shard` duck-types it
    (``plan`` / ``shard`` / ``staging_dir`` / ``renew`` / ``park`` /
    ``commit``); ``scratch`` is this worker's PRIVATE local directory
    for staging drains — never shared, wiped freely."""

    def __init__(self, store, prefix: str, scratch=None,
                 worker: str | None = None, lease_ttl: float = 60.0,
                 clock=_time.time):
        self.store = store
        self.prefix = str(prefix).strip("/")
        self.scratch = str(
            scratch if scratch is not None
            else tempfile.mkdtemp(prefix="tpudas-backfill-")
        )
        os.makedirs(self.scratch, exist_ok=True)
        self.worker = str(
            worker if worker is not None
            else f"{os.uname().nodename}.{os.getpid()}"
        )
        self.lease_ttl = float(lease_ttl)
        self.clock = clock
        self.plan = load_plan_store(store, self.prefix)
        self._claim_seq = 0
        # lease object tokens as last read/written by THIS worker:
        # renew CASes against them
        self._lease_tokens: dict = {}

    # -- keys / paths --------------------------------------------------
    def _key(self, *parts) -> str:
        rel = "/".join(str(p) for p in parts)
        return f"{self.prefix}/{rel}" if self.prefix else rel

    def shard(self, shard_id: str) -> dict:
        for sh in self.plan["shards"]:
            if sh["id"] == shard_id:
                return sh
        raise KeyError(f"unknown shard {shard_id!r}")

    def shard_prefix(self, shard_id: str) -> str:
        return self._key(SHARDS_PREFIX, shard_id)

    def staging_dir(self, lease: Lease) -> str:
        return os.path.join(
            self.scratch, f"{lease.shard}.work.{lease.token}"
        )

    def _lease_key(self, shard_id: str) -> str:
        return self._key(LEASES_PREFIX, shard_id + ".json")

    def _done_key(self, shard_id: str) -> str:
        return self._key(DONE_PREFIX, shard_id + ".json")

    def _parked_key(self, shard_id: str) -> str:
        return self._key(PARKED_PREFIX, shard_id + ".json")

    def _manifest_key(self, shard_id: str) -> str:
        return f"{self.shard_prefix(shard_id)}/{SHARD_MANIFEST_NAME}"

    # -- state reads ---------------------------------------------------
    def _now_ns(self) -> int:
        return int(float(self.clock()) * 1e9)

    def _get_verified(self, key: str):
        """``(payload, store_token)`` or ``(None, None)`` for one
        coordination object (absent or torn both read as None — a
        torn lease protects nothing)."""
        try:
            data, token = self.store.get(key)
        except ObjectNotFoundError:
            return None, None
        payload, ok = _loads_verified(data)
        return (payload, token) if ok else (None, token)

    def read_lease(self, shard_id: str) -> dict | None:
        payload, token = self._get_verified(self._lease_key(shard_id))
        # memoize the OBJECT token unconditionally (None when absent):
        # claiming over a torn lease replaces it by CAS, and a vanished
        # lease must clear the memo or later CASes chase a ghost
        self._lease_tokens[shard_id] = token
        return payload

    def is_done(self, shard_id: str) -> bool:
        return self._get_verified(self._done_key(shard_id))[0] is not None

    def is_parked(self, shard_id: str) -> bool:
        return self.store.head(self._parked_key(shard_id)) is not None

    def shard_manifest(self, shard_id: str) -> dict | None:
        return self._get_verified(self._manifest_key(shard_id))[0]

    def manifest_verifies(self, shard_id: str) -> bool:
        """True when the shard's upload manifest exists and every
        object it names is present with its recorded token — the
        object-store equivalent of "the renamed directory exists"."""
        manifest = self.shard_manifest(shard_id)
        if manifest is None:
            return False
        base = self.shard_prefix(shard_id)
        for name, tok in manifest.get("objects", {}).items():
            if self.store.head(f"{base}/{name}") != tok:
                return False
        return True

    def shard_state(self, shard_id: str) -> str:
        """Same vocabulary as the POSIX queue: ``done`` | ``parked``
        | ``adoptable`` (verifying upload manifest, no marker, no
        live lease) | ``leased`` | ``stale`` | ``open``."""
        if self.is_done(shard_id):
            return "done"
        if self.is_parked(shard_id):
            return "parked"
        lease = self.read_lease(shard_id)
        live = (
            lease is not None
            and int(lease.get("deadline_ns", 0)) >= self._now_ns()
        )
        if live:
            return "leased"
        if self.shard_manifest(shard_id) is not None:
            return "adoptable"
        return "open" if lease is None else "stale"

    def counts(self) -> dict:
        counts = {
            "done": 0, "parked": 0, "adoptable": 0,
            "leased": 0, "stale": 0, "open": 0,
        }
        for sh in self.plan["shards"]:
            counts[self.shard_state(sh["id"])] += 1
        return counts

    def resolved(self) -> bool:
        return all(
            self.shard_state(sh["id"]) in ("done", "parked")
            for sh in self.plan["shards"]
        )

    def all_done(self) -> bool:
        return all(self.is_done(sh["id"]) for sh in self.plan["shards"])

    # -- claim / renew / release --------------------------------------
    def try_claim(self, shard_id: str) -> Lease | None:
        """Claim an open shard (create-only put) or steal a stale one
        (CAS on the stale lease's object token).  The store serializes
        racing claimers: exactly one conditional put wins, no settle
        window."""
        t0 = _time.perf_counter()
        reg = get_registry()
        state = self.shard_state(shard_id)
        if state not in ("open", "stale", "adoptable"):
            return None
        lease_key = self._lease_key(shard_id)
        with span("backfill.claim", shard=shard_id):
            fault_point("backfill.claim", path=lease_key, shard=shard_id)
            now = self._now_ns()
            token = f"{self.worker}.{os.getpid()}.{self._claim_seq}"
            self._claim_seq += 1
            payload = {
                "shard": shard_id,
                "worker": self.worker,
                "pid": os.getpid(),
                "token": token,
                "heartbeat_ns": now,
                "deadline_ns": now + int(self.lease_ttl * 1e9),
                "stolen": state == "stale",
            }
            # shard_state above just refreshed the memo: None = no
            # lease object (create-only claim), a token = stale or
            # torn lease object (atomic CAS steal)
            stale_token = self._lease_tokens.get(shard_id)
            try:
                if stale_token is None:
                    obj_token = self.store.put_if(
                        lease_key, _dumps(payload), if_absent=True
                    )
                else:
                    obj_token = self.store.put_if(
                        lease_key, _dumps(payload), if_token=stale_token
                    )
            except CASConflictError:
                reg.counter(
                    "tpudas_backfill_claim_conflicts_total",
                    "shard claims lost to another worker's concurrent "
                    "lease write (the settle re-read disagreed)",
                ).inc()
                return None
        self._lease_tokens[shard_id] = obj_token
        if state == "stale":
            reg.counter(
                "tpudas_backfill_shards_reclaimed_total",
                "shards reclaimed from a stale lease (the previous "
                "worker died or wedged; the shard is re-executed)",
            ).inc()
            log_event(
                "backfill_shard_reclaimed", shard=shard_id,
                worker=self.worker, previous="stale-lease",
            )
        lease = Lease(shard=shard_id, token=token, worker=self.worker)
        lease.overhead_s += _time.perf_counter() - t0
        return lease

    def claim_next(self) -> Lease | None:
        for sh in self.plan["shards"]:
            lease = self.try_claim(sh["id"])
            if lease is not None:
                return lease
        return None

    def renew(self, lease: Lease) -> None:
        """CAS the lease forward on its object token; any conflict or
        foreign token is a definitive steal —
        :class:`LeaseLostError`."""
        t0 = _time.perf_counter()
        current = self.read_lease(lease.shard)
        if current is None or current.get("token") != lease.token:
            raise LeaseLostError(
                f"lease on {lease.shard} lost to "
                f"{None if current is None else current.get('worker')!r}"
            )
        now = self._now_ns()
        try:
            self._lease_tokens[lease.shard] = self.store.put_if(
                self._lease_key(lease.shard),
                _dumps({
                    **current,
                    "heartbeat_ns": now,
                    "deadline_ns": now + int(self.lease_ttl * 1e9),
                }),
                if_token=self._lease_tokens.get(lease.shard),
            )
        except CASConflictError as exc:
            raise LeaseLostError(
                f"lease on {lease.shard} CAS-stolen mid-renew"
            ) from exc
        get_registry().counter(
            "tpudas_backfill_lease_renewals_total",
            "shard lease heartbeat renewals",
        ).inc()
        lease.overhead_s += _time.perf_counter() - t0

    def release(self, lease: Lease) -> None:
        current = self.read_lease(lease.shard)
        if current is not None and current.get("token") == lease.token:
            try:
                self.store.delete(self._lease_key(lease.shard))
            except OSError as exc:
                log_event(
                    "backfill_lease_release_failed", shard=lease.shard,
                    error=f"{type(exc).__name__}: {str(exc)[:120]}",
                )

    # -- commit / adopt / park ----------------------------------------
    def _upload_staging(self, shard_id: str, staging: str) -> dict:
        """Steps (1) and (2) of the commit protocol: every staged
        file as an object, then the upload manifest naming them all.
        Returns the manifest payload."""
        objects = {}
        base = self.shard_prefix(shard_id)
        for dirpath, _dirnames, filenames in os.walk(staging):
            rel_dir = os.path.relpath(dirpath, staging)
            for name in sorted(filenames):
                if ".tmp." in name:
                    continue
                rel = (
                    name if rel_dir == "."
                    else f"{rel_dir.replace(os.sep, '/')}/{name}"
                )
                with open(os.path.join(dirpath, name), "rb") as fh:
                    data = fh.read()
                objects[rel] = self.store.put(f"{base}/{rel}", data)
        manifest = {
            "shard": shard_id,
            "objects": objects,
            "count": len(objects),
        }
        self.store.put(self._manifest_key(shard_id), _dumps(manifest))
        return manifest

    def _write_done(self, shard_id: str, lease: Lease, extra: dict) -> (
        bool
    ):
        """Step (3): the create-only marker.  True = this execution
        IS the commit; False = another execution's marker stands."""
        payload = {
            "shard": shard_id,
            "worker": lease.worker,
            "token": lease.token,
            "committed_ns": self._now_ns(),
            **extra,
        }
        try:
            self.store.put_if(
                self._done_key(shard_id), _dumps(payload), if_absent=True
            )
            return True
        except CASConflictError:
            return False

    def commit(self, lease: Lease, staging: str, **extra) -> str:
        """Upload-then-mark exactly-once commit (see module doc).
        Returns ``"committed"`` | ``"lost"``; either way the local
        staging directory is consumed."""
        t0 = _time.perf_counter()
        reg = get_registry()
        with span("backfill.commit", shard=lease.shard):
            fault_point(
                "backfill.commit",
                path=self.shard_prefix(lease.shard), shard=lease.shard,
            )
            manifest = self._upload_staging(lease.shard, staging)
            lease.overhead_s += _time.perf_counter() - t0
            won = self._write_done(
                lease.shard, lease,
                {
                    "overhead_s": round(lease.overhead_s, 6),
                    "objects": int(manifest["count"]),
                    **extra,
                },
            )
            shutil.rmtree(staging, ignore_errors=True)
            self.release(lease)
        if not won:
            reg.counter(
                "tpudas_backfill_double_commits_total",
                "shard or stitch executions that lost the "
                "commit-wins rename (their staging was discarded)",
            ).inc()
            log_event(
                "backfill_commit_lost", shard=lease.shard,
                worker=self.worker,
            )
            return "lost"
        reg.counter(
            "tpudas_backfill_shards_committed_total",
            "shards committed exactly-once (rename + done marker)",
        ).inc()
        reg.counter(
            "tpudas_backfill_overhead_seconds_total",
            "wall seconds spent in lease claim/renew/commit "
            "bookkeeping (the <2%-of-shard-wall budget)",
        ).inc(lease.overhead_s)
        log_event(
            "backfill_shard_committed", shard=lease.shard,
            worker=self.worker,
            **{k: v for k, v in extra.items() if k != "digests"},
        )
        return "committed"

    def adopt(self, lease: Lease, **extra) -> str:
        """Finish a crashed commit: a verifying upload manifest
        without its marker gets the marker; anything less re-executes
        (``"failed"`` — the debris is overwritten idempotently by the
        re-run's uploads)."""
        if self.is_done(lease.shard):
            self.release(lease)
            return "committed"
        if not self.manifest_verifies(lease.shard):
            # mid-upload crash: delete the manifest (if any) so the
            # shard re-executes cleanly over the debris
            self.store.delete(self._manifest_key(lease.shard))
            self.release(lease)
            log_event("backfill_adopt_failed", shard=lease.shard,
                      issues=-1)
            return "failed"
        won = self._write_done(lease.shard, lease,
                               {"adopted": True, **extra})
        self.release(lease)
        if won:
            get_registry().counter(
                "tpudas_backfill_shards_committed_total",
                "shards committed exactly-once (rename + done marker)",
            ).inc()
            log_event("backfill_shard_adopted", shard=lease.shard)
        return "committed"

    def park(self, lease: Lease, exc: BaseException, kind: str) -> None:
        payload = {
            "shard": lease.shard,
            "worker": self.worker,
            "kind": kind,
            "error": f"{type(exc).__name__}: {str(exc)[:300]}",
            "parked_ns": self._now_ns(),
        }
        try:
            self.store.put_if(
                self._parked_key(lease.shard), _dumps(payload),
                if_absent=True,
            )
        except CASConflictError:
            pass  # another worker parked it first — same verdict
        self.release(lease)
        get_registry().counter(
            "tpudas_backfill_shards_parked_total",
            "shards parked after a terminal execution failure "
            "(fsck-able; the worker keeps draining the rest)",
        ).inc()
        log_event(
            "backfill_shard_parked", shard=lease.shard, kind=kind,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )

    # -- materialization (stitch / serve reads) -----------------------
    def materialize_shard(self, shard_id: str, dest: str) -> int:
        """Download one committed shard's objects into ``dest`` (the
        stitcher's local working copy); token-verified against the
        upload manifest.  Returns the object count."""
        manifest = self.shard_manifest(shard_id)
        if manifest is None:
            raise ObjectNotFoundError(self._manifest_key(shard_id))
        base = self.shard_prefix(shard_id)
        os.makedirs(dest, exist_ok=True)
        for rel, tok in manifest.get("objects", {}).items():
            data, got = self.store.get(f"{base}/{rel}")
            if got != tok:
                raise ValueError(
                    f"shard {shard_id} object {rel!r} token {got!r} != "
                    f"manifest {tok!r} (torn or tampered upload)"
                )
            path = os.path.join(dest, *rel.split("/"))
            os.makedirs(os.path.dirname(path) or dest, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(data)
            os.replace(tmp, path)
        return int(manifest.get("count", 0))


def stitch_store_backfill(store, prefix: str, queue=None,
                          worker: str | None = None,
                          scratch=None) -> dict:
    """The deterministic stitch over an object-store queue: download
    committed shards to local scratch, reuse the POSIX stitcher's row
    merge/pyramid/detect machinery verbatim, upload the result, and
    commit with a create-only marker (commit-wins, any worker may
    race)."""
    from tpudas.backfill.stitch import _shard_window, _write_rows
    from tpudas.io.spool import spool as make_spool

    if queue is None:
        queue = StoreBackfillQueue(
            store, prefix, scratch=scratch, worker=worker
        )
    done_key = queue._key(RESULT_DONE_KEY)
    if store.head(done_key) is not None:
        return {"status": "already", "result": queue._key(RESULT_PREFIX)}
    if not queue.all_done():
        counts = queue.counts()
        log_event("backfill_unstitchable", **counts)
        return {"status": "unstitchable", "counts": counts}
    plan = queue.plan
    cfg = plan["config"]
    token = f"{queue.worker}.{os.getpid()}"
    staging = os.path.join(
        queue.scratch, f"{RESULT_PREFIX}.work.{token}"
    )
    if os.path.isdir(staging):
        shutil.rmtree(staging)
    os.makedirs(staging)
    t0 = _time.perf_counter()
    rows_total = files_total = 0
    with span("backfill.stitch", shards=len(plan["shards"])):
        shard_scratch = os.path.join(queue.scratch, "stitch-shards")
        for idx, sh in enumerate(plan["shards"]):
            sdir = os.path.join(shard_scratch, sh["id"])
            if not os.path.isdir(sdir):
                queue.materialize_shard(sh["id"], sdir)
            lo, hi = _shard_window(plan, idx)
            sp = make_spool(sdir).sort("time").update()
            if lo is not None or hi is not None:
                sp = sp.select(time=(lo, hi))
            rows, files = _write_rows(staging, sp.chunk(time=None))
            rows_total += rows
            files_total += files
        if cfg.get("pyramid"):
            from tpudas.serve.tiles import sync_pyramid

            sync_pyramid(staging)
        if cfg.get("detect") and cfg.get("detect_operators"):
            from tpudas.detect.runner import DetectPipeline

            ops = tuple(
                (name, dict(params))
                for name, params in cfg["detect_operators"]
            )
            pipe = DetectPipeline.open(
                staging, operators=ops,
                step_sec=float(cfg["output_sample_interval"]),
            )
            pipe.process_round([])
        from tpudas.backfill.runner import scrub_index_cache

        scrub_index_cache(staging)
        fault_point(
            "backfill.commit", path=queue._key(RESULT_PREFIX),
            shard="result",
        )
        # upload the result + its manifest, then the create-only
        # marker — same three-step protocol as a shard commit
        objects = {}
        for dirpath, _dirnames, filenames in os.walk(staging):
            rel_dir = os.path.relpath(dirpath, staging)
            for name in sorted(filenames):
                if ".tmp." in name:
                    continue
                rel = (
                    name if rel_dir == "."
                    else f"{rel_dir.replace(os.sep, '/')}/{name}"
                )
                with open(os.path.join(dirpath, name), "rb") as fh:
                    data = fh.read()
                objects[rel] = store.put(
                    queue._key(RESULT_PREFIX, rel), data
                )
        store.put(
            queue._key(RESULT_MANIFEST_KEY),
            _dumps({"objects": objects, "count": len(objects)}),
        )
        marker = {
            "worker": queue.worker,
            "rows": int(rows_total),
            "files": int(files_total),
            "shards": len(plan["shards"]),
            "wall_s": round(_time.perf_counter() - t0, 4),
        }
        shutil.rmtree(staging, ignore_errors=True)
        try:
            store.put_if(done_key, _dumps(marker), if_absent=True)
        except CASConflictError:
            get_registry().counter(
                "tpudas_backfill_double_commits_total",
                "shard or stitch executions that lost the "
                "commit-wins rename (their staging was discarded)",
            ).inc()
            return {
                "status": "already",
                "result": queue._key(RESULT_PREFIX),
            }
    get_registry().counter(
        "tpudas_backfill_stitch_rows_total",
        "output rows stitched into committed backfill results",
    ).inc(rows_total)
    log_event(
        "backfill_stitched", root=f"store:{queue.prefix}",
        rows=rows_total, files=files_total, shards=len(plan["shards"]),
    )
    return {
        "status": "committed",
        "result": queue._key(RESULT_PREFIX),
        "rows": rows_total,
    }


def run_store_worker(store, prefix: str, scratch=None,
                     worker: str | None = None, stitch: bool = True,
                     idle_poll: float = 0.25,
                     max_wall: float | None = None,
                     sleep_fn=_time.sleep, **queue_kwargs) -> dict:
    """One object-store backfill worker, end to end — the exact
    :func:`~tpudas.backfill.runner.run_worker` loop (claim → adopt or
    drain → commit → stitch race) on the store substrate.  The worker
    shares NOTHING with its peers but the store."""
    from tpudas.backfill.runner import execute_shard

    queue = StoreBackfillQueue(
        store, prefix, scratch=scratch, worker=worker, **queue_kwargs
    )
    tally = {
        "worker": queue.worker, "committed": 0, "adopted": 0,
        "lost": 0, "parked": 0, "stitched": False,
    }
    t0 = _time.perf_counter()
    while True:
        if max_wall is not None and _time.perf_counter() - t0 > max_wall:
            raise TimeoutError(
                f"backfill worker exceeded max_wall={max_wall}s "
                f"with queue counts {queue.counts()}"
            )
        lease = queue.claim_next()
        if lease is None:
            if queue.resolved():
                break
            sleep_fn(idle_poll)
            continue
        if queue.manifest_verifies(lease.shard):
            # a crashed commit (uploads + manifest landed, marker
            # missing): adopt instead of re-draining
            outcome = queue.adopt(lease)
            if outcome == "committed":
                tally["adopted"] += 1
            continue
        try:
            outcome = execute_shard(queue, lease, sleep_fn=sleep_fn)
        except LeaseLostError as exc:
            log_event(
                "backfill_lease_lost", shard=lease.shard,
                worker=queue.worker, error=str(exc)[:200],
            )
            continue
        tally[outcome] = tally.get(outcome, 0) + 1
    if stitch and queue.all_done():
        result = stitch_store_backfill(store, prefix, queue=queue)
        tally["stitched"] = result["status"] in ("committed", "already")
        tally["stitch_status"] = result["status"]
    # replicated store: before this worker exits, push any writes a
    # down mirror missed (the handoff journal) at mirrors that have
    # healed meanwhile — workers drain their own debt, scrub only
    # mops up after crashes
    from tpudas.store.replica import find_replicated

    repl = find_replicated(store)
    if repl is not None:
        tally["handoff_drained"] = repl.drain_handoff()
    tally["counts"] = queue.counts()
    log_event("backfill_worker_done", **{
        k: v for k, v in tally.items() if k != "counts"
    })
    return tally
