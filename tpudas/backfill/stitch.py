"""Deterministic stitching: N committed shards → one sequential result.

Once every shard is committed, the stitched result is a pure function
of the plan and the shard bytes — so ANY worker (or all of them,
racing) can build it, and a 4-worker chaos run must produce a result
**byte-identical** to a single-worker uninterrupted control:

1. **Rows.**  For each shard in plan order, read its output files and
   keep only the rows on ``[t0, t1)`` (the first shard keeps its
   initial transient, the last keeps its tail) — the warm-up lead
   made every kept row bit-identical to the sequential run's, so
   concatenation IS the sequential output.  Rows are written to the
   result as one deterministic file per contiguous segment per shard
   (file *boundaries* differ from a realtime run's round-schedule
   chunking, which is why output equality is judged on merged content
   — exactly the crash drill's rule).
2. **Pyramid.**  ``sync_pyramid`` over the stitched files — the
   offline oracle the realtime incremental append is already proven
   byte-identical to, so the tile/tails/manifest bytes match a live
   run's.
3. **Detect.**  A fresh :class:`~tpudas.detect.runner.DetectPipeline`
   file-backed catch-up over the stitched rows — operators are
   chunk-invariant by contract, so the events ledger and score tiles
   are byte-identical to a live run's.
4. **Commit.**  The same commit-wins discipline as shards: build in
   ``result.work.<token>``, one atomic rename to ``result/``, then
   the crc-stamped ``result.done.json`` marker (a crash between the
   two is adopted by ``audit_backfill``).
"""

from __future__ import annotations

import os
import shutil
import time as _time

import numpy as np

from tpudas.backfill.queue import (
    RESULT_DIRNAME,
    RESULT_DONE_FILENAME,
    BackfillQueue,
    commit_rename,
)
from tpudas.integrity.checksum import write_json_checksummed
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.resilience.faults import fault_point
from tpudas.utils.logging import log_event

__all__ = ["stitch_backfill"]


def _write_rows(staging: str, patches) -> tuple[int, int]:
    """Write merged patches as result output files; returns
    (rows, files)."""
    from tpudas.io.registry import write_patch
    from tpudas.proc.naming import get_filename

    rows = files = 0
    for patch in patches:
        taxis = patch.coords["time"]
        if taxis.size == 0:
            continue
        name = get_filename(patch.attrs["time_min"], patch.attrs["time_max"])
        write_patch(patch, os.path.join(staging, name), "dasdae")
        rows += int(taxis.size)
        files += 1
    return rows, files


def _shard_window(plan: dict, idx: int):
    """The keep-window for shard ``idx``: ``[t0, t1)`` as inclusive
    ns datetime64 select bounds (``t1 - 1 ns`` so the boundary row
    belongs to exactly one shard); open at the archive's ends so the
    first shard keeps the initial transient and the last its tail."""
    shards = plan["shards"]
    sh = shards[idx]
    lo = (
        None if idx == 0
        else np.datetime64(int(sh["t0_ns"]), "ns")
    )
    hi = (
        None if idx == len(shards) - 1
        else np.datetime64(int(sh["t1_ns"]) - 1, "ns")
    )
    return lo, hi


def _write_result_marker(done_path, queue, rows, files, shards,
                         wall_s, adopted=False) -> None:
    payload = {
        "worker": queue.worker,
        "rows": int(rows),
        "files": int(files),
        "shards": int(shards),
        "wall_s": round(float(wall_s), 4),
    }
    if adopted:
        payload["adopted"] = True
    write_json_checksummed(done_path, payload, durable=True)


def _adopt_result(root, queue, final, done_path) -> dict | None:
    """Finish a crashed stitch commit: ``result/`` exists (the rename
    landed — a complete stitch by construction) but the marker is
    missing.  Verify the directory and write the marker, mirroring
    the shard commit's adoption; a directory that does not verify is
    removed so the next call re-stitches.  Returns the status dict,
    or None when the adoption failed (re-stitch)."""
    from tpudas.integrity.audit import audit

    report = audit(final, repair=True)
    if not report["clean"]:
        shutil.rmtree(final, ignore_errors=True)
        log_event(
            "backfill_result_adopt_failed",
            root=root,
            issues=len(report["issues"]),
        )
        return None
    _write_result_marker(
        done_path, queue, rows=0, files=0,
        shards=len(queue.plan["shards"]), wall_s=0.0, adopted=True,
    )
    log_event("backfill_result_adopted", root=root)
    return {"status": "committed", "result": final, "adopted": True}


def stitch_backfill(root, queue: BackfillQueue | None = None,
                    worker: str | None = None) -> dict:
    """Build + commit the stitched result for a fully-drained queue.
    Returns a status dict: ``committed`` | ``already`` (a result is
    already committed) | ``unstitchable`` (parked/unresolved shards
    remain — counted in the payload).  A ``result/`` directory
    without its marker (a stitcher crashed between the rename and
    the marker write) is **adopted** — verified and marked — rather
    than re-stitched; losing the commit-wins rename takes the same
    adoption path, so the marker always lands."""
    from tpudas.io.spool import spool as make_spool

    root = str(root)
    if queue is None:
        queue = BackfillQueue(root, worker=worker)
    done_path = os.path.join(root, RESULT_DONE_FILENAME)
    final = os.path.join(root, RESULT_DIRNAME)
    if os.path.isfile(done_path):
        return {"status": "already", "result": final}
    if os.path.isdir(final):
        # a crashed stitcher's commit window: rename landed, marker
        # missing — adopt instead of rebuilding and losing forever
        adopted = _adopt_result(root, queue, final, done_path)
        if adopted is not None:
            return adopted
    if not queue.all_done():
        counts = queue.counts()
        log_event("backfill_unstitchable", **counts)
        return {"status": "unstitchable", "counts": counts}
    plan = queue.plan
    cfg = plan["config"]
    token = f"{queue.worker}.{os.getpid()}"
    staging = os.path.join(root, f"{RESULT_DIRNAME}.work.{token}")
    if os.path.isdir(staging):
        shutil.rmtree(staging)
    os.makedirs(staging)
    t0 = _time.perf_counter()
    rows_total = files_total = 0
    with span("backfill.stitch", shards=len(plan["shards"])):
        for idx, sh in enumerate(plan["shards"]):
            sdir = queue.shard_dir(sh["id"])
            lo, hi = _shard_window(plan, idx)
            sp = make_spool(sdir).sort("time").update()
            if lo is not None or hi is not None:
                sp = sp.select(time=(lo, hi))
            rows, files = _write_rows(staging, sp.chunk(time=None))
            rows_total += rows
            files_total += files
        if cfg.get("pyramid"):
            from tpudas.serve.tiles import sync_pyramid

            sync_pyramid(staging)
        if cfg.get("detect") and cfg.get("detect_operators"):
            from tpudas.detect.runner import DetectPipeline

            ops = tuple(
                (name, dict(params))
                for name, params in cfg["detect_operators"]
            )
            pipe = DetectPipeline.open(
                staging, operators=ops,
                step_sec=float(cfg["output_sample_interval"]),
            )
            pipe.process_round([])
        from tpudas.backfill.runner import scrub_index_cache

        scrub_index_cache(staging)
        # the stitch commit: same commit-wins rename discipline as a
        # shard's (and the same fault site, so the drill can kill it)
        fault_point("backfill.commit", path=final, shard="result")
        if not commit_rename(staging, final):
            # another stitcher's rename won; discard our staging and
            # make sure THEIR marker lands (they may have crashed in
            # their commit window — adoption keeps the queue unwedged)
            shutil.rmtree(staging, ignore_errors=True)
            get_registry().counter(
                "tpudas_backfill_double_commits_total",
                "shard or stitch executions that lost the "
                "commit-wins rename (their staging was discarded)",
            ).inc()
            if os.path.isfile(done_path):
                return {"status": "already", "result": final}
            adopted = _adopt_result(root, queue, final, done_path)
            if adopted is not None:
                return adopted
            return {"status": "lost", "result": final}
        _write_result_marker(
            done_path, queue, rows=rows_total, files=files_total,
            shards=len(plan["shards"]),
            wall_s=_time.perf_counter() - t0,
        )
    get_registry().counter(
        "tpudas_backfill_stitch_rows_total",
        "output rows stitched into committed backfill results",
    ).inc(rows_total)
    log_event(
        "backfill_stitched",
        root=root,
        rows=rows_total,
        files=files_total,
        shards=len(plan["shards"]),
    )
    return {"status": "committed", "result": final, "rows": rows_total}
