"""The filesystem-backed backfill work queue: shards, leases, commits.

One backfill job lives entirely under one **root directory** on a
filesystem every worker can reach (NFS, a shared volume — the same
place the fleet already keeps per-stream state)::

    root/
      backfill.json            # the crc-stamped plan (written once)
      shards/<id>/             # committed shard output (atomic rename)
      shards/<id>.work.<tok>/  # a claim's private staging directory
      .leases/<id>.json        # the live lease (worker, pid, deadline)
      .done/<id>.json          # the crc-stamped exactly-once marker
      .parked/<id>.json        # fatal-shard park record (fsck-able)
      result/                  # the stitched result (tpudas.backfill.stitch)
      result.done.json         # the stitch's commit marker

**The plan** (:func:`plan_backfill`) splits an archive slice
``[t0, t1)`` into time shards on the output grid.  Each shard is one
:class:`tpudas.fleet.config.StreamSpec`-shaped job: drain the archive
slice ``[t0 - lead, t1 + lead]`` through the streaming engine into a
private staging directory.  ``lead`` (default two edge buffers,
rounded up to the output grid) is the warm-up margin that rebuilds the
FIR cascade's finite state exactly, so a shard's rows inside
``[t0, t1)`` are bit-identical to a single sequential run's — the
same rewind argument the drivers' crash-resume already proves.

**Leases.**  A worker claims a shard by atomically writing
``.leases/<id>.json`` (worker id, pid, token, heartbeat, deadline),
re-reading after a settle to confirm its token survived (two racing
claimers: last write wins whole, the loser backs off — counted).  The
worker renews the lease every drain round; ANY worker may reclaim a
shard whose lease deadline has passed, so a SIGKILLed or wedged
worker's shards are re-executed, not lost.  The lease is an
*optimization*, never the correctness mechanism: double execution is
resolved by the commit-wins rule below, so clock skew across hosts
costs duplicated work at worst.  Size ``lease_ttl`` comfortably above
one drain round (``ingest_limit_sec`` bounds the round).

**Exactly-once commit.**  A drained, audit-clean staging directory is
committed by ONE atomic ``os.rename(staging, shards/<id>)`` — the
filesystem refuses the second rename, so exactly one execution's
bytes become the shard, no matter how many workers raced —
followed by the crc-stamped ``.done/<id>.json`` marker.  A crash
between the two leaves a committed directory without a marker; the
next claimer (or ``audit_backfill``) *adopts* it: re-verify the
directory, write the marker, done.  Re-execution is therefore
idempotent end to end: claim → drain → rename-or-lose → marker.

Fault sites: ``backfill.claim`` fires at the head of every
claim/steal write, ``backfill.commit`` just before the rename —
``tools/backfill_drill.py`` kills workers at both.
"""

from __future__ import annotations

import math
import os
import shutil
import time as _time
from dataclasses import dataclass

from tpudas.integrity.checksum import (
    read_json_verified,
    write_json_checksummed,
)
from tpudas.obs.registry import get_registry
from tpudas.obs.trace import span
from tpudas.resilience.faults import fault_point
from tpudas.utils.logging import log_event

__all__ = [
    "DONE_DIRNAME",
    "LEASES_DIRNAME",
    "PARKED_DIRNAME",
    "PLAN_FILENAME",
    "RESULT_DIRNAME",
    "RESULT_DONE_FILENAME",
    "SHARDS_DIRNAME",
    "BackfillQueue",
    "Lease",
    "LeaseLostError",
    "build_plan",
    "load_plan",
    "plan_backfill",
]

PLAN_FILENAME = "backfill.json"
SHARDS_DIRNAME = "shards"
LEASES_DIRNAME = ".leases"
DONE_DIRNAME = ".done"
PARKED_DIRNAME = ".parked"
RESULT_DIRNAME = "result"
RESULT_DONE_FILENAME = "result.done.json"

_PLAN_VERSION = 1
# config keys the plan persists verbatim (all JSON-serializable; the
# worker rebuilds a StreamConfig from them per shard)
_PLAN_CONFIG_KEYS = (
    "output_sample_interval",
    "edge_buffer",
    "process_patch_size",
    "engine",
    "distance",
    "pyramid",
    "detect",
    "detect_operators",
    "on_gap",
    "filter_order",
    "data_gap_tolerance",
)


def commit_rename(staging: str, final: str) -> bool:
    """The exactly-once primitive both shard and stitch commits
    share: ONE atomic ``os.rename(staging, final)``.  Returns True
    when this execution's rename won; False when another execution's
    ``final`` already stands (commit-wins — the caller discards its
    staging).  Any rename failure that is NOT the commit-wins race
    re-raises."""
    if os.path.isdir(final):
        return False
    try:
        os.rename(staging, final)
    except OSError:
        # the commit-wins race: final appeared between the check and
        # our rename — anything else is a real error
        if not os.path.isdir(final):
            raise
        return False
    return True


class LeaseLostError(RuntimeError):
    """This worker's lease was stolen (stale deadline + reclaim) —
    abandon the shard mid-drain; the thief re-executes it and the
    orphaned staging directory is swept by ``audit_backfill``."""


@dataclass
class Lease:
    """One live claim: identity + the running overhead account the
    done marker records (claim + renew + commit bookkeeping wall)."""

    shard: str
    token: str
    worker: str
    overhead_s: float = 0.0


def _ns(t) -> int:
    import numpy as np

    from tpudas.core.timeutils import to_datetime64

    return int(
        to_datetime64(t).astype("datetime64[ns]").astype(np.int64)
    )


def _grid_ceil(seconds: float, d_t: float) -> float:
    """``seconds`` rounded UP to the output grid (lead/shard lengths
    must be grid multiples or the shard's decimation phase — and with
    it byte-identity — breaks)."""
    return math.ceil(float(seconds) / float(d_t) - 1e-9) * float(d_t)


def _source_step_sec(source) -> float | None:
    """The archive's input sample step, from the index alone."""
    import numpy as np

    from tpudas.io.spool import spool as make_spool

    try:
        contents = make_spool(source).update().get_contents()
        row = contents.iloc[0]
        span_ns = (
            np.datetime64(row["time_max"], "ns")
            - np.datetime64(row["time_min"], "ns")
        ) / np.timedelta64(1, "ns")
        n_time = int(row["ntime"])
        if n_time < 2 or span_ns <= 0:
            return None
        return float(span_ns / 1e9 / (n_time - 1))
    except Exception as exc:
        log_event(
            "backfill_source_probe_failed",
            source=str(source),
            error=f"{type(exc).__name__}: {str(exc)[:120]}",
        )
        return None


def default_leads(source, d_t, edge_buffer, order=None) -> tuple:
    """(head_lead, tail_lead) seconds for one shard, derived from the
    actual cascade plan over the archive's sample rate.

    *Head*: a shard opens its stream cold at ``t0 - head_lead`` with
    a ``plan.delay``-sample zero prepad (the stream feed origin); its
    emitted rows become bit-identical to the sequential run's once
    that prepad has fully flushed through every cascade stage's
    carried state — ``delay/ratio`` output steps after the stream
    start (measured exact: taint ends at ``start + ceil(delay/ratio)``
    steps).  *Tail*: the stateful engine's emitted head trails the
    ingested head by ``(warmup + 1 - delay/ratio)`` output steps
    (tpudas.ops.fir stream formulation), so the input slice must
    extend that far past ``t1`` for the kept rows to reach it.

    Falls back to ``(2*edge, 2*edge) + a generous warmup guess`` when
    the plan cannot be designed (fft engine, non-integer ratio) —
    stitching still works there, but byte-identity to a sequential
    run is only promised for the chunk-invariant cascade/fused
    engines anyway."""
    d_t = float(d_t)
    edge = float(edge_buffer)
    buff_out = math.ceil(edge / d_t)
    d_in = _source_step_sec(source)
    if d_in is not None and d_in > 0:
        ratio = d_t / d_in
        if abs(ratio - round(ratio)) < 1e-9:
            try:
                from tpudas.ops.fir import (
                    design_cascade,
                    stream_warmup_outputs,
                )
                from tpudas.proc.lfproc import output_corner

                plan = design_cascade(
                    1.0 / d_in, int(round(ratio)), output_corner(d_t),
                    4 if order is None else int(order),
                )
                warmup = stream_warmup_outputs(plan)
                delay_steps = plan.delay / float(plan.ratio)
                head = _grid_ceil((delay_steps + 3) * d_t, d_t)
                tail = _grid_ceil(
                    (warmup + 2 - delay_steps) * d_t + 2 * d_t, d_t
                )
                return max(head, _grid_ceil(buff_out * d_t, d_t)), (
                    max(tail, d_t)
                )
            except Exception as exc:
                log_event(
                    "backfill_lead_plan_failed",
                    error=f"{type(exc).__name__}: {str(exc)[:120]}",
                )
    # conservative fallback: no plan to consult
    return _grid_ceil(4 * edge, d_t), _grid_ceil(8 * edge, d_t)


def build_plan(
    source,
    t0,
    t1,
    shard_seconds: float,
    output_sample_interval: float,
    edge_buffer: float,
    process_patch_size: int,
    engine=None,
    distance=None,
    pyramid: bool = True,
    detect: bool = False,
    detect_operators=None,
    lead_seconds: float | None = None,
    tail_seconds: float | None = None,
    ingest_limit_sec: float | None = 600.0,
    **extra_config,
) -> dict:
    """The pure planning step: cut ``[t0, t1)`` into shards, derive
    the warm-up leads, and return the plan dict — no filesystem or
    store touched (beyond probing the SOURCE archive for lead
    derivation).  :func:`plan_backfill` persists it to a directory
    root; the object-store queue persists the same dict as a
    create-only object."""
    d_t = float(output_sample_interval)
    t0_ns, t1_ns = _ns(t0), _ns(t1)
    if t1_ns <= t0_ns:
        raise ValueError(f"empty archive slice: t1 {t1!r} <= t0 {t0!r}")
    shard_sec = _grid_ceil(shard_seconds, d_t)
    if shard_sec <= 0:
        raise ValueError(f"shard_seconds must be > 0, got {shard_seconds}")
    if lead_seconds is None or tail_seconds is None:
        head_auto, tail_auto = default_leads(
            source, d_t, edge_buffer,
            order=extra_config.get("filter_order"),
        )
        if lead_seconds is None:
            lead_seconds = head_auto
        if tail_seconds is None:
            tail_seconds = tail_auto
    lead_sec = _grid_ceil(lead_seconds, d_t)
    tail_sec = _grid_ceil(tail_seconds, d_t)
    shard_ns = int(round(shard_sec * 1e9))
    shards = []
    k = 0
    lo = t0_ns
    while lo < t1_ns:
        hi = min(lo + shard_ns, t1_ns)
        shards.append({"id": f"sh{k:05d}", "t0_ns": lo, "t1_ns": hi})
        lo = hi
        k += 1
    config = {
        "output_sample_interval": d_t,
        "edge_buffer": float(edge_buffer),
        "process_patch_size": int(process_patch_size),
        "engine": engine,
        "distance": distance,
        "pyramid": bool(pyramid),
        "detect": bool(detect),
        "detect_operators": detect_operators,
        **extra_config,
    }
    unknown = sorted(set(config) - set(_PLAN_CONFIG_KEYS))
    if unknown:
        raise ValueError(f"unknown backfill config key(s): {unknown}")
    return {
        "version": _PLAN_VERSION,
        "source": os.path.abspath(str(source)),
        "t0_ns": t0_ns,
        "t1_ns": t1_ns,
        "shard_seconds": shard_sec,
        "lead_seconds": lead_sec,
        "tail_seconds": tail_sec,
        "ingest_limit_sec": (
            None if ingest_limit_sec is None else float(ingest_limit_sec)
        ),
        "config": config,
        "shards": shards,
    }


def plan_backfill(root, source, t0, t1, **kwargs) -> dict:
    """Write the crc-stamped plan for one backfill job and return it.

    The archive slice ``[t0, t1)`` is cut into shards of
    ``shard_seconds`` (rounded up to the output grid; the last shard
    takes the remainder).  ``lead_seconds`` is the per-shard warm-up
    margin (default derived from the cascade plan, grid-rounded).  The
    remaining keywords mirror the lowpass driver knobs the workers
    rebuild a :class:`~tpudas.fleet.config.StreamConfig` from (see
    :func:`build_plan`); ``pyramid`` / ``detect`` are applied at
    STITCH time (shards themselves write only output files + carry —
    serve/detect state near a cold shard boundary would differ from
    the sequential run's, so it is derived once, deterministically,
    from the stitched rows).

    Raises ``FileExistsError`` when the root already holds a plan —
    a queue is immutable once written (workers may already be
    claiming against it).
    """
    root = str(root)
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, PLAN_FILENAME)
    if os.path.isfile(path):
        raise FileExistsError(
            f"{path} already exists; a backfill plan is immutable "
            "(make a new root to re-plan)"
        )
    plan = build_plan(source, t0, t1, **kwargs)
    write_json_checksummed(path, plan, durable=True)
    for d in (SHARDS_DIRNAME, LEASES_DIRNAME, DONE_DIRNAME, PARKED_DIRNAME):
        os.makedirs(os.path.join(root, d), exist_ok=True)
    get_registry().gauge(
        "tpudas_backfill_shards", "time shards in the backfill plan"
    ).set(len(plan["shards"]))
    log_event(
        "backfill_planned",
        root=root,
        shards=len(plan["shards"]),
        shard_seconds=plan["shard_seconds"],
        lead_seconds=plan["lead_seconds"],
        tail_seconds=plan["tail_seconds"],
    )
    return plan


def load_plan(root) -> dict:
    """Read + verify the plan; raises on a missing/torn plan (a queue
    whose plan cannot be trusted must not be drained)."""
    path = os.path.join(str(root), PLAN_FILENAME)
    payload, status = read_json_verified(path, "backfill_plan")
    if status == "mismatch":
        raise ValueError(f"backfill plan {path} failed its crc32 check")
    if int(payload.get("version", -1)) != _PLAN_VERSION:
        raise ValueError(
            f"unknown backfill plan version {payload.get('version')!r}"
        )
    return payload


class BackfillQueue:
    """Lease/commit operations for one worker over one backfill root.

    ``clock`` (seconds, ``time.time``) is injectable so lease-expiry
    tests need no real waiting; ``settle`` is the claim's
    write-then-reread confirmation delay (0 in single-threaded
    tests)."""

    def __init__(
        self,
        root,
        worker: str | None = None,
        lease_ttl: float = 60.0,
        settle: float = 0.05,
        clock=_time.time,
    ):
        self.root = str(root)
        self.worker = str(
            worker
            if worker is not None
            else f"{os.uname().nodename}.{os.getpid()}"
        )
        self.lease_ttl = float(lease_ttl)
        self.settle = float(settle)
        self.clock = clock
        self.plan = load_plan(self.root)
        self._claim_seq = 0

    # -- paths ---------------------------------------------------------
    def shard(self, shard_id: str) -> dict:
        for sh in self.plan["shards"]:
            if sh["id"] == shard_id:
                return sh
        raise KeyError(f"unknown shard {shard_id!r}")

    def shard_dir(self, shard_id: str) -> str:
        return os.path.join(self.root, SHARDS_DIRNAME, shard_id)

    def staging_dir(self, lease: Lease) -> str:
        return os.path.join(
            self.root, SHARDS_DIRNAME,
            f"{lease.shard}.work.{lease.token}",
        )

    def _lease_path(self, shard_id: str) -> str:
        return os.path.join(self.root, LEASES_DIRNAME, shard_id + ".json")

    def _done_path(self, shard_id: str) -> str:
        return os.path.join(self.root, DONE_DIRNAME, shard_id + ".json")

    def _parked_path(self, shard_id: str) -> str:
        return os.path.join(self.root, PARKED_DIRNAME, shard_id + ".json")

    # -- state reads ---------------------------------------------------
    def _now_ns(self) -> int:
        return int(float(self.clock()) * 1e9)

    def read_lease(self, shard_id: str) -> dict | None:
        """The current lease payload, or None when absent/torn (a torn
        lease is claimable — it protects nothing)."""
        try:
            payload, status = read_json_verified(
                self._lease_path(shard_id), "backfill_lease"
            )
        except (OSError, ValueError):
            return None
        return None if status == "mismatch" else payload

    def is_done(self, shard_id: str) -> bool:
        try:
            _, status = read_json_verified(
                self._done_path(shard_id), "backfill_done"
            )
        except (OSError, ValueError):
            return False
        return status != "mismatch"

    def is_parked(self, shard_id: str) -> bool:
        return os.path.isfile(self._parked_path(shard_id))

    def shard_state(self, shard_id: str) -> str:
        """``done`` | ``parked`` | ``adoptable`` (committed directory
        without its marker — a crash between rename and marker) |
        ``leased`` | ``stale`` (lease expired) | ``open``.

        The lease is consulted BEFORE the directory: a live lease
        over a committed directory is a worker INSIDE its commit
        (between the rename and the marker write) — clobbering it
        would let a second worker adopt concurrently and overwrite
        the committer's marker.  Only an expired (or absent) lease
        makes the directory adoptable."""
        if self.is_done(shard_id):
            return "done"
        if self.is_parked(shard_id):
            return "parked"
        lease = self.read_lease(shard_id)
        live = (
            lease is not None
            and int(lease.get("deadline_ns", 0)) >= self._now_ns()
        )
        if os.path.isdir(self.shard_dir(shard_id)):
            return "leased" if live else "adoptable"
        if lease is None:
            return "open"
        return "leased" if live else "stale"

    def counts(self) -> dict:
        counts = {
            "done": 0, "parked": 0, "adoptable": 0,
            "leased": 0, "stale": 0, "open": 0,
        }
        for sh in self.plan["shards"]:
            counts[self.shard_state(sh["id"])] += 1
        return counts

    def resolved(self) -> bool:
        """Every shard is done or parked — nothing left to execute."""
        return all(
            self.shard_state(sh["id"]) in ("done", "parked")
            for sh in self.plan["shards"]
        )

    def all_done(self) -> bool:
        return all(self.is_done(sh["id"]) for sh in self.plan["shards"])

    # -- claim / renew / release --------------------------------------
    def try_claim(self, shard_id: str) -> Lease | None:
        """Claim (or reclaim) one shard: write the lease, settle,
        re-read, confirm the token survived.  Returns None when the
        shard is not claimable or the settle re-read shows another
        worker won the write race."""
        t0 = _time.perf_counter()
        reg = get_registry()
        state = self.shard_state(shard_id)
        if state not in ("open", "stale", "adoptable"):
            return None
        lease_path = self._lease_path(shard_id)
        with span("backfill.claim", shard=shard_id):
            fault_point("backfill.claim", path=lease_path, shard=shard_id)
            now = self._now_ns()
            token = f"{self.worker}.{os.getpid()}.{self._claim_seq}"
            self._claim_seq += 1
            write_json_checksummed(
                lease_path,
                {
                    "shard": shard_id,
                    "worker": self.worker,
                    "pid": os.getpid(),
                    "token": token,
                    "heartbeat_ns": now,
                    "deadline_ns": now + int(self.lease_ttl * 1e9),
                    "stolen": state == "stale",
                },
            )
            if self.settle:
                _time.sleep(self.settle)
            current = self.read_lease(shard_id)
        if current is None or current.get("token") != token:
            reg.counter(
                "tpudas_backfill_claim_conflicts_total",
                "shard claims lost to another worker's concurrent "
                "lease write (the settle re-read disagreed)",
            ).inc()
            return None
        if state == "stale":
            reg.counter(
                "tpudas_backfill_shards_reclaimed_total",
                "shards reclaimed from a stale lease (the previous "
                "worker died or wedged; the shard is re-executed)",
            ).inc()
            log_event(
                "backfill_shard_reclaimed",
                shard=shard_id,
                worker=self.worker,
                previous=str(current.get("stolen", "")),
            )
        lease = Lease(shard=shard_id, token=token, worker=self.worker)
        lease.overhead_s += _time.perf_counter() - t0
        return lease

    def claim_next(self) -> Lease | None:
        """The next claimable shard in plan order, or None when no
        shard is currently claimable (all done/parked/validly
        leased)."""
        for sh in self.plan["shards"]:
            lease = self.try_claim(sh["id"])
            if lease is not None:
                return lease
        return None

    def renew(self, lease: Lease) -> None:
        """Extend this worker's lease; raises :class:`LeaseLostError`
        when another worker reclaimed it (stop draining — the thief's
        execution is now authoritative)."""
        t0 = _time.perf_counter()
        current = self.read_lease(lease.shard)
        if current is None or current.get("token") != lease.token:
            raise LeaseLostError(
                f"lease on {lease.shard} lost to "
                f"{None if current is None else current.get('worker')!r}"
            )
        now = self._now_ns()
        write_json_checksummed(
            self._lease_path(lease.shard),
            {
                **current,
                "heartbeat_ns": now,
                "deadline_ns": now + int(self.lease_ttl * 1e9),
            },
        )
        get_registry().counter(
            "tpudas_backfill_lease_renewals_total",
            "shard lease heartbeat renewals",
        ).inc()
        lease.overhead_s += _time.perf_counter() - t0

    def release(self, lease: Lease) -> None:
        """Drop this worker's lease (only if still ours — never
        clobber a thief's live lease)."""
        current = self.read_lease(lease.shard)
        if current is not None and current.get("token") == lease.token:
            try:
                os.remove(self._lease_path(lease.shard))
            except OSError as exc:
                log_event(
                    "backfill_lease_release_failed",
                    shard=lease.shard,
                    error=f"{type(exc).__name__}: {str(exc)[:120]}",
                )

    # -- commit / park -------------------------------------------------
    def _write_done(self, shard_id, lease, extra) -> None:
        write_json_checksummed(
            self._done_path(shard_id),
            {
                "shard": shard_id,
                "worker": lease.worker,
                "token": lease.token,
                "committed_ns": self._now_ns(),
                **extra,
            },
            durable=True,
        )

    def commit(self, lease: Lease, staging: str, **extra) -> str:
        """The exactly-once commit: atomically rename ``staging`` to
        the shard directory, then write the done marker.  Returns
        ``"committed"``, or ``"lost"`` when another execution's rename
        won (commit-wins: this worker's staging is discarded, the
        marker — written by the winner or adopted — stands).  Extra
        keywords (wall_s, rounds, ...) are recorded in the marker."""
        t0 = _time.perf_counter()
        reg = get_registry()
        final = self.shard_dir(lease.shard)
        with span("backfill.commit", shard=lease.shard):
            fault_point("backfill.commit", path=final, shard=lease.shard)
            if not commit_rename(staging, final):
                reg.counter(
                    "tpudas_backfill_double_commits_total",
                    "shard or stitch executions that lost the "
                    "commit-wins rename (their staging was discarded)",
                ).inc()
                shutil.rmtree(staging, ignore_errors=True)
                self.release(lease)
                log_event(
                    "backfill_commit_lost",
                    shard=lease.shard,
                    worker=self.worker,
                )
                return "lost"
            lease.overhead_s += _time.perf_counter() - t0
            self._write_done(
                lease.shard, lease,
                {"overhead_s": round(lease.overhead_s, 6), **extra},
            )
            self.release(lease)
        reg.counter(
            "tpudas_backfill_shards_committed_total",
            "shards committed exactly-once (rename + done marker)",
        ).inc()
        reg.counter(
            "tpudas_backfill_overhead_seconds_total",
            "wall seconds spent in lease claim/renew/commit "
            "bookkeeping (the <2%-of-shard-wall budget)",
        ).inc(lease.overhead_s)
        log_event(
            "backfill_shard_committed",
            shard=lease.shard,
            worker=self.worker,
            **{k: v for k, v in extra.items() if k != "digests"},
        )
        return "committed"

    def adopt(self, lease: Lease, **extra) -> str:
        """Finish a crashed commit: the shard directory exists (the
        rename landed) but the marker is missing — verify the
        directory and write the marker.  Returns ``"committed"`` or
        ``"failed"`` (directory does not verify: it is removed so the
        shard re-executes)."""
        from tpudas.integrity.audit import audit

        if self.is_done(lease.shard):
            # the original committer's marker landed after our claim
            # (a wedged worker finishing late): its record stands
            self.release(lease)
            return "committed"
        final = self.shard_dir(lease.shard)
        report = audit(final, repair=True)
        if not report["clean"]:
            shutil.rmtree(final, ignore_errors=True)
            self.release(lease)
            log_event(
                "backfill_adopt_failed",
                shard=lease.shard,
                issues=len(report["issues"]),
            )
            return "failed"
        self._write_done(
            lease.shard, lease, {"adopted": True, **extra}
        )
        self.release(lease)
        get_registry().counter(
            "tpudas_backfill_shards_committed_total",
            "shards committed exactly-once (rename + done marker)",
        ).inc()
        log_event("backfill_shard_adopted", shard=lease.shard)
        return "committed"

    def park(self, lease: Lease, exc: BaseException, kind: str) -> None:
        """Park a shard whose execution failed terminally (fatal
        fault, exhausted retries): the shard is counted, fsck-able,
        and skipped by every claimer — the worker moves on instead of
        dying.  The queue can never stitch while parked shards
        remain."""
        write_json_checksummed(
            self._parked_path(lease.shard),
            {
                "shard": lease.shard,
                "worker": self.worker,
                "kind": kind,
                "error": f"{type(exc).__name__}: {str(exc)[:300]}",
                "parked_ns": self._now_ns(),
            },
            durable=True,
        )
        self.release(lease)
        get_registry().counter(
            "tpudas_backfill_shards_parked_total",
            "shards parked after a terminal execution failure "
            "(fsck-able; the worker keeps draining the rest)",
        ).inc()
        log_event(
            "backfill_shard_parked",
            shard=lease.shard,
            kind=kind,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
